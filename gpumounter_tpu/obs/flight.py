"""Incident flight recorder: one chronological timeline per process.

After an incident the operator's first question is "what happened, in
order?" — and before this module the answer was scattered across four
stores with four query surfaces (spans in the trace ring, mutations in
the audit trail, k8s Events in the cluster, ApiHealth verdicts in
/apihealth) plus log files. The flight recorder merges the control
plane's significant moments into ONE bounded, durably-spillable
timeline:

  * root and error spans (via a tracer exporter — child spans stay in
    the trace ring where /trace/<id> tells their detailed story),
  * every audit record (via the audit log's subscriber hook),
  * every Kubernetes Event this process posts (k8s/events.py + the SLO
    engine's breach Events),
  * ApiHealth state transitions (k8s/health.py subscriber),
  * recovery/evacuation markers (recovery/controller.py).

Queryable at GET /timeline?node=&trace=&kind=&from=&to=&limit= on the
master (and the worker ops port) and as `tpumounter timeline`; each
entry carries the trace id that was ambient when it was recorded, so
the walkthrough is timeline -> trace -> audit (docs/RUNBOOK.md
"Reconstructing an incident with the flight recorder").

Bounded in memory (TPUMOUNTER_FLIGHT_CAPACITY); with a spill path
configured (TPUMOUNTER_FLIGHT_JSONL) every record is also appended to
an append-only JSONL file so a post-mortem can reach past the ring —
same write-failure discipline as the audit sink (log once, disable,
never fail the operation being recorded).

Stdlib-only (lazy-grpc policy: this is on the mount path via the span
exporter).
"""

from __future__ import annotations

import itertools
import time
from collections import deque

from gpumounter_tpu.obs import trace
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.metrics import REGISTRY

FLIGHT_RECORDS = REGISTRY.counter(
    "tpumounter_flight_records_total",
    "Flight-recorder timeline records by kind (span / audit / event / "
    "apihealth / recovery / health / marker)")

#: the bounded record-kind vocabulary (the `kind` label rides on
#: FLIGHT_RECORDS; anything else is folded to "marker").
KINDS = frozenset({"span", "audit", "event", "apihealth", "recovery",
                   "health", "marker"})


class FlightRecorder:
    """Thread-safe bounded chronological record store."""

    def __init__(self, capacity: int = 4096):
        from gpumounter_tpu.obs.sinks import JsonlSink
        self._records: deque[dict] = deque(maxlen=capacity)
        self._lock = OrderedLock("flight.records")
        self._seq = itertools.count(1)
        self._jsonl = JsonlSink("flight")

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._records = deque(self._records, maxlen=max(1, capacity))

    def configure_jsonl(self, path: str) -> None:
        self._jsonl.configure(path)

    def record(self, kind: str, summary: str, node: str = "",
               trace_id: str | None = None, at: float | None = None,
               **details) -> dict:
        """Append one timeline record. trace_id defaults to the ambient
        one (records written inside a span join that trace's story);
        `at` defaults to now — sources that know their own timestamp
        (a span's start) pass it so the merge stays chronological."""
        kind = kind if kind in KINDS else "marker"
        rec = {
            "seq": next(self._seq),
            "at": round(time.time() if at is None else at, 6),
            "kind": kind,
            "node": node,
            "trace_id": trace.current_trace_id()
            if trace_id is None else trace_id,
            "summary": summary,
        }
        if details:
            rec["details"] = {k: v for k, v in details.items()}
        with self._lock:
            self._records.append(rec)
        self._jsonl.write(rec)
        FLIGHT_RECORDS.inc(kind=kind)
        return rec

    def query(self, node: str | None = None, trace_id: str | None = None,
              kind: str | None = None, since: float | None = None,
              until: float | None = None, limit: int = 500) -> list[dict]:
        """Chronological (oldest-first) filtered view; with more matches
        than `limit`, the NEWEST `limit` win — an incident review reads
        toward the present."""
        with self._lock:
            records = list(self._records)
        records.sort(key=lambda r: (r["at"], r["seq"]))
        out = []
        for rec in records:
            if node and rec.get("node") != node:
                continue
            if trace_id and rec.get("trace_id") != trace_id:
                continue
            if kind and rec.get("kind") != kind:
                continue
            if since is not None and rec["at"] < since:
                continue
            if until is not None and rec["at"] > until:
                continue
            out.append(dict(rec))
        return out[-max(1, limit):]

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._records]

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._jsonl.configure("")


FLIGHT = FlightRecorder()


def query_from_params(params: dict[str, list[str]],
                      recorder: FlightRecorder | None = None) -> dict:
    """The /timeline query contract, shared by the master route, the
    worker ops port and the CLI so the surfaces cannot drift:
    last-value-wins params `node`/`trace`/`kind`/`from`/`to`/`limit`.
    Raises ValueError on non-numeric from/to/limit."""

    def _one(key: str) -> str | None:
        values = params.get(key)
        return values[-1] if values else None

    def _stamp(key: str) -> float | None:
        raw = _one(key)
        return float(raw) if raw is not None else None

    sink = recorder or FLIGHT
    return {"records": sink.query(
        node=_one("node"), trace_id=_one("trace"), kind=_one("kind"),
        since=_stamp("from"), until=_stamp("to"),
        limit=int(_one("limit") or 500))}


def configure(cfg) -> None:
    """Daemon-startup wiring (master/worker main): record capacity and
    the optional JSONL spill from config."""
    FLIGHT.set_capacity(cfg.flight_capacity)
    if cfg.flight_jsonl:
        FLIGHT.configure_jsonl(cfg.flight_jsonl)


# --- source hooks ---


class _SpanFlightExporter:
    """Root and error spans become timeline records; child ok-spans
    stay in the trace ring (the timeline is the table of contents, the
    trace is the chapter)."""

    def export(self, span: dict) -> None:
        is_root = not (span.get("parent_id") or "")
        failed = span.get("status") == "error"
        if not is_root and not failed:
            return
        name = span.get("name", "")
        duration_ms = round(float(span.get("duration_s", 0.0)) * 1000.0, 3)
        summary = f"{name} {span.get('status', '')} ({duration_ms}ms)"
        attrs = span.get("attrs") or {}
        FLIGHT.record(
            "span", summary,
            node=str(attrs.get("node", "")),
            trace_id=span.get("trace_id", ""),
            at=span.get("start"),
            span_id=span.get("span_id", ""),
            duration_ms=duration_ms,
            **({"error": span["error"]} if span.get("error") else {}))


_SPAN_EXPORTER = _SpanFlightExporter()


def _on_audit_record(rec: dict) -> None:
    pod = f"{rec.get('namespace', '')}/{rec.get('pod', '')}".strip("/")
    summary = f"{rec.get('operation', '')} -> {rec.get('outcome', '')}" \
              + (f" [{pod}]" if pod else "")
    FLIGHT.record("audit", summary, trace_id=rec.get("trace_id", ""),
                  at=rec.get("at"), operation=rec.get("operation", ""),
                  outcome=rec.get("outcome", ""), actor=rec.get("actor", ""))


def _on_apihealth(old_state: str, new_state: str) -> None:
    FLIGHT.record("apihealth", f"kube API {old_state} -> {new_state}",
                  old=old_state, new=new_state)


def install(tracer=None, apihealth=None) -> None:
    """Idempotent hook registration: the span exporter onto the tracer,
    the audit subscriber onto the global audit log, and (when given)
    the ApiHealth transition subscriber. Called from MasterApp /
    TpuMountService construction so any live daemon — and any test that
    builds one — records its timeline without extra wiring; safe to
    call repeatedly (each sink deduplicates by identity)."""
    from gpumounter_tpu.obs.audit import AUDIT
    (tracer or trace.TRACER).add_exporter(_SPAN_EXPORTER)
    AUDIT.subscribe(_on_audit_record)
    if apihealth is not None:
        apihealth.subscribe(_on_apihealth)
