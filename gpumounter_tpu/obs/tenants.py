"""Worker-side store for tenant telemetry snapshots.

Tenants (gpumounter_tpu/jaxside/telemetry.py) POST cumulative snapshots
to the worker's ops port (/tenant-telemetry, mutate scope). This store
keeps the latest snapshot per tenant, bounded by the same 256 +
`_overflow` convention the device-access telemetry table established
(cgroup/ebpf.py): tenant names come from user-controlled pod names, the
classic unbounded-cardinality trap — beyond `max_tenants` distinct
tenants, later ones fold into one `_overflow` entry (latest snapshot
wins, with a count of how many were folded) so neither the worker's
memory nor the fleet payload can explode.

The worker's CollectTelemetry snapshot embeds `export()` under a
"tenants" key; the FleetCollector merges those fleet-wide
(obs/fleet.py). Values stay cumulative end to end, so the no-double-
counting contract (chaos invariant 8) extends to tenant series.
"""

from __future__ import annotations

import time

from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("obs.tenants")

TENANT_SCHEMA = "tpumounter-tenant/1"
OVERFLOW_TENANT = "_overflow"

TENANT_SNAPSHOTS = REGISTRY.counter(
    "tpumounter_tenant_snapshots_total",
    "Tenant telemetry snapshots accepted on the ops port (no tenant "
    "label by design — per-tenant series live in the JSON plane, "
    "bounded by the store's 256 + _overflow cap)")
TENANT_SNAPSHOTS_REJECTED = REGISTRY.counter(
    "tpumounter_tenant_snapshots_rejected_total",
    "Tenant telemetry POSTs rejected (bad schema / malformed JSON)")
TENANTS_TRACKED = REGISTRY.gauge(
    "tpumounter_tenants_tracked",
    "Distinct tenants with a stored snapshot (overflow bucket counts "
    "as one)")


def parse_tenant_snapshot(raw: object) -> dict | None:
    """Tolerant body parse: anything that is not a schema-tagged JSON
    object with a non-empty tenant name yields None, never raises —
    the ops handler answers 400 and moves on."""
    import json
    if isinstance(raw, (bytes, bytearray)):
        try:
            raw = raw.decode()
        except UnicodeDecodeError:
            return None
    if not raw or not isinstance(raw, str):
        return None
    try:
        doc = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(doc, dict) or doc.get("schema") != TENANT_SCHEMA:
        return None
    if not doc.get("tenant") or not isinstance(doc["tenant"], str):
        return None
    return doc


class TenantStore:
    """Latest-snapshot-per-tenant, cardinality-capped."""

    def __init__(self, max_tenants: int = 256):
        self.max_tenants = max_tenants
        self._lock = OrderedLock("tenants.store")
        self._snapshots: dict[str, dict] = {}
        self._received_at: dict[str, float] = {}
        self._overflow_folded: set[str] = set()

    def _key_for(self, tenant: str) -> str:
        if tenant in self._snapshots or \
                len(self._snapshots) < self.max_tenants:
            return tenant
        return OVERFLOW_TENANT

    def ingest(self, snapshot: dict) -> str:
        """Store a parsed snapshot; returns the key it landed under
        (the tenant name, or _overflow past the cap)."""
        tenant = snapshot["tenant"]
        with self._lock:
            key = self._key_for(tenant)
            if key == OVERFLOW_TENANT:
                self._overflow_folded.add(tenant)
                snapshot = {**snapshot, "tenant": OVERFLOW_TENANT,
                            "folded_tenants": len(self._overflow_folded)}
            self._snapshots[key] = snapshot
            self._received_at[key] = time.time()
            TENANTS_TRACKED.set(float(len(self._snapshots)))
        TENANT_SNAPSHOTS.inc()
        return key

    def export(self) -> dict[str, dict]:
        """tenant -> latest snapshot (with the worker's received_at
        stamp) — the "tenants" block of the CollectTelemetry payload."""
        with self._lock:
            return {key: {**snap,
                          "received_at": round(self._received_at[key], 3)}
                    for key, snap in self._snapshots.items()}

    def tenant_count(self) -> int:
        with self._lock:
            return len(self._snapshots)

    def reset(self) -> None:
        with self._lock:
            self._snapshots.clear()
            self._received_at.clear()
            self._overflow_folded.clear()
        TENANTS_TRACKED.set(0.0)


#: the worker process's store (module-global like DEVICE_TELEMETRY —
#: one per daemon; tests construct their own bounded instances).
TENANTS = TenantStore()
