"""Operations observability: distributed tracing + audit trail.

The control plane mutates running pods from three cooperating planes
(slice ops, elastic reconciler, migration orchestrator). This package
answers the operator question those planes cannot: "what happened to
pod X's chips, when, and why was it slow" —

  * obs.trace — contextvar-based spans with a trace id minted at the
    master HTTP edge and propagated over the RPC wire to the worker
    (rpc/api.py trace_context fields), covering every phase of
    mount/unmount/heal/migrate; in-memory ring-buffer + JSONL exporters.
  * obs.audit — an append-only structured record of every mutating
    operation (actor, pod, chips, idempotency key, outcome, duration,
    trace id), queryable via the master's /audit route and the
    `tpumounter audit` / `tpumounter trace <id>` CLI verbs.
  * obs.fleet — master-side federation of every worker's telemetry
    (CollectTelemetry RPC over the pooled channels, HTTP-scrape
    fallback for legacy workers) into a node-keyed fleet rollup
    served at /fleet and by `tpumounter fleet`.
  * obs.slo — declarative objectives with multi-window burn-rate
    evaluation over the fleet rollup (/slo, `tpumounter slo`);
    breaches post k8s Events and audit records — latency breaches
    stamped with the fleet-dominant critical-path phase.
  * obs.assembly — fleet-wide trace assembly: worker span rings ride
    the CollectTelemetry snapshot into a master-side RemoteSpanStore;
    assemble() joins both halves into an end-to-end operation tree
    with per-phase critical-path attribution (served by the upgraded
    /trace/<id> waterfall and `tpumounter why <trace-id>`).
  * obs.flight — the incident flight recorder: root/error spans,
    audit records, k8s Events, ApiHealth transitions and recovery
    markers merged into one bounded, durably-spillable chronological
    timeline (/timeline, `tpumounter timeline`).

Stdlib-only on purpose: imported by the mount path, which must stay
importable without grpc (utils/lazy_grpc.py policy — obs.fleet takes
its RPC transport as an injected client factory).
"""

from gpumounter_tpu.obs import audit, trace

__all__ = ["audit", "trace"]
