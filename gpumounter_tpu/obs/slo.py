"""Declarative SLOs with multi-window burn-rate evaluation.

An Objective declares a good-events target over the fleet rollup
(obs/fleet.py): either a latency objective ("this fraction of mounts
must complete within threshold_s", computed from the merged mount
histogram's buckets) or a ratio objective (good vs bad counter keys,
e.g. heal success). The engine keeps cumulative (good, total) samples
per objective and evaluates the burn rate — the fraction of the error
budget being consumed — over two windows:

    burn = (bad / total within window) / (1 - target)

A breach requires the burn to exceed the threshold over BOTH the fast
window (react within minutes) and the slow window (ignore blips) with
observed traffic in the fast window — the standard multiwindow
multi-burn-rate alerting shape. Breach transitions emit a Kubernetes
Event (reason TPUSLOBurnRate) and an audit record carrying the
evaluation's trace id, so "the pager fired" joins the same story the
/audit and /trace routes tell.

Counter resets (worker restarts) can only shrink cumulative values;
window deltas are clamped at zero so a restart reads as "no traffic",
never as negative burn. Stdlib-only.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import asdict, dataclass, field

from gpumounter_tpu.obs import trace
from gpumounter_tpu.obs.audit import AUDIT
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("obs.slo")

SLO_BURN_RATE = REGISTRY.gauge(
    "tpumounter_slo_burn_rate",
    "Error-budget burn rate by objective and window (1.0 = consuming "
    "budget exactly at the sustainable rate)")
SLO_BREACHES = REGISTRY.counter(
    "tpumounter_slo_breaches_total",
    "Multi-window burn-rate breach transitions by objective")
SLO_BREACHED = REGISTRY.gauge(
    "tpumounter_slo_breached",
    "1 while the objective is in breach (both windows over threshold)")


class ObjectiveError(ValueError):
    """An SLO objective declaration is invalid."""


@dataclass(frozen=True)
class Objective:
    name: str
    kind: str                 # "latency" | "ratio" | "tenant-downtime"
    target: float             # good fraction target in (0, 1)
    threshold_s: float = 0.0  # latency kinds: the bound that is "good"
    good: str = ""            # ratio: rollup counter key for good events
    bad: str = ""             # ratio: rollup counter key for bad events
    #: tenant-downtime: which disruption cause's merged downtime
    #: histogram to judge (migration / heal / evacuation / ...).
    cause: str = "migration"
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("latency", "ratio", "tenant-downtime"):
            raise ObjectiveError(f"{self.name}: unknown kind {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ObjectiveError(
                f"{self.name}: target must be in (0, 1), got {self.target}")
        if self.kind in ("latency", "tenant-downtime") \
                and self.threshold_s <= 0:
            raise ObjectiveError(f"{self.name}: {self.kind} needs "
                                 f"threshold_s")
        if self.kind == "ratio" and not (self.good and self.bad):
            raise ObjectiveError(f"{self.name}: ratio needs good and bad keys")


#: the built-in objectives (overridable via TPUMOUNTER_SLO_OBJECTIVES):
#: warm-mount latency (the PR 5 fast path's p95 < 50 ms promise, stated
#: as "95% of mounts within 50 ms"), mount success, and heal success.
DEFAULT_OBJECTIVES: tuple[Objective, ...] = (
    Objective(name="mount-latency-50ms", kind="latency", threshold_s=0.05,
              target=0.95,
              description="95% of hot-mounts complete within 50 ms "
                          "(warm-path latency promise)"),
    Objective(name="mount-success", kind="ratio", target=0.999,
              good="mount_success", bad="mount_error",
              description="99.9% of mount operations succeed"),
    Objective(name="heal-success", kind="ratio", target=0.99,
              good="heals", bad="heal_failures",
              description="99% of chip heals succeed"),
    # Tenant-perceived objectives (the jaxside telemetry plane,
    # obs/fleet.py tenants_fleet rollup). Zero tenant traffic = zero
    # burn, so fleets without the SDK never see these breach.
    Objective(name="tenant-migration-downtime", kind="tenant-downtime",
              cause="migration", threshold_s=2.5, target=0.95,
              description="95% of tenant-visible migration disruption "
                          "windows close within 2.5 s (p95 "
                          "tenant-visible migration downtime)"),
    Objective(name="tenant-disruption-free-minutes", kind="ratio",
              target=0.999, good="tenant_clean_minutes",
              bad="tenant_disrupted_minutes",
              description="99.9% of tenant wall-clock minutes are "
                          "disruption-free"),
    # Capacity plane (obs/capacity.py): every collection pass evaluates
    # per-accelerator-size admissibility (sizes the fleet could host).
    # Bad events are FRAGMENTATION-caused denials only — the free
    # chips exist but no ICI-contiguous blocks do — so a fully-utilized
    # fleet never pages here (that's the headroom forecast's story);
    # burn means a defrag pass would unlock blocked slice shapes.
    # Fleets without a capacity plane wired see zero traffic and never
    # breach.
    Objective(name="slice-feasibility", kind="ratio", target=0.9,
              good="slice_feasible", bad="slice_infeasible",
              description="90% of per-pass accelerator-size "
                          "feasibility evaluations are not denied by "
                          "fragmentation alone (large-block "
                          "admissibility: burn means defrag would "
                          "unlock blocked slice shapes)"),
)


def objectives_from_config(cfg) -> tuple[Objective, ...]:
    """TPUMOUNTER_SLO_OBJECTIVES (a JSON list of Objective fields) or
    the defaults. A malformed declaration fails loudly at startup —
    silently alerting on nothing would be worse than not booting."""
    raw = getattr(cfg, "slo_objectives", "") or ""
    if not raw.strip():
        return DEFAULT_OBJECTIVES
    try:
        docs = json.loads(raw)
    except ValueError as exc:
        raise ObjectiveError(f"TPUMOUNTER_SLO_OBJECTIVES is not JSON: {exc}")
    if not isinstance(docs, list):
        raise ObjectiveError("TPUMOUNTER_SLO_OBJECTIVES must be a JSON list")
    return tuple(Objective(**doc) for doc in docs)


def _good_within(buckets, threshold_s: float) -> float:
    """Cumulative count at the largest bucket bound <= threshold — the
    'fast enough' events of a cumulative histogram."""
    good = 0.0
    best_bound = None
    for bound, cum in buckets or []:
        if float(bound) <= threshold_s + 1e-12 and \
                (best_bound is None or float(bound) > best_bound):
            best_bound = float(bound)
            good = float(cum)
    return good


def _good_total(objective: Objective, rollup: dict) -> tuple[float, float]:
    """Cumulative (good, total) for one objective from a fleet rollup."""
    fleet = rollup.get("fleet") or {}
    if objective.kind == "latency":
        total = float(fleet.get("mount_count", 0))
        return _good_within(fleet.get("mount_buckets"),
                            objective.threshold_s), total
    if objective.kind == "tenant-downtime":
        # good = tenant disruption windows (of this cause) that closed
        # within the threshold, from the fleet-merged per-cause
        # downtime histogram (obs/fleet.py tenants_fleet_rollup).
        downtime = ((rollup.get("tenants_fleet") or {})
                    .get("downtime") or {}).get(objective.cause) or {}
        total = float(downtime.get("count", 0))
        return _good_within(downtime.get("buckets"),
                            objective.threshold_s), total
    counters = {**(rollup.get("master") or {}),
                "mount_success": fleet.get("mount_success", 0.0),
                "mount_error": fleet.get("mount_error", 0.0)}
    for key in ("tenant_clean_minutes", "tenant_disrupted_minutes"):
        counters[key] = float(
            (rollup.get("tenants_fleet") or {}).get(key, 0.0))
    good = float(counters.get(objective.good, 0.0))
    bad = float(counters.get(objective.bad, 0.0))
    return good, good + bad


@dataclass
class _ObjectiveState:
    objective: Objective
    #: cumulative (monotonic time, good, total) samples, newest last
    samples: deque = field(default_factory=lambda: deque(maxlen=4096))
    breached: bool = False


class SloEngine:
    """Ingests fleet rollups, evaluates burn rates, emits breaches."""

    def __init__(self, cfg=None, kube=None,
                 objectives: tuple[Objective, ...] | None = None,
                 clock=time.monotonic):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.cfg = cfg
        self.kube = kube
        self.clock = clock
        self.fast_window_s = cfg.slo_fast_window_s
        self.slow_window_s = cfg.slo_slow_window_s
        self.burn_threshold = cfg.slo_burn_threshold
        self._states = {
            o.name: _ObjectiveState(o)
            for o in (objectives if objectives is not None
                      else objectives_from_config(cfg))}
        self._evaluated_at = 0.0
        # The background collector thread ingests while /slo request
        # threads evaluate: sample deques and breach-state transitions
        # share one lock (breach emission — Event POST, audit — runs
        # outside it so a slow API server cannot stall ingestion).
        self._lock = OrderedLock("slo.states")

    # --- sampling ---

    def ingest(self, rollup: dict) -> None:
        """Record one fleet rollup's cumulative counts (called by the
        FleetCollector after every pass). Idempotent per pass — values
        are cumulative, so re-ingesting the same rollup adds a sample
        with identical counts, never double-counts events."""
        now = self.clock()
        with self._lock:
            for state in self._states.values():
                good, total = _good_total(state.objective, rollup)
                state.samples.append((now, good, total))

    def _window_burn(self, state: _ObjectiveState, now: float,
                     window_s: float) -> tuple[float, float]:
        """(burn rate, total events) over the trailing window. Baseline
        is the newest sample at or before the window start — or zero
        when history is shorter than the window (an engine that just
        started alerts on everything it has seen, by design: a breach
        in progress must not hide behind a restart)."""
        samples = list(state.samples)
        if not samples:
            return 0.0, 0.0
        latest_t, latest_good, latest_total = samples[-1]
        base_good = base_total = 0.0
        for t, good, total in reversed(samples):
            if now - t >= window_s:
                base_good, base_total = good, total
                break
        # clamp: a counter reset (worker restart) shrinks cumulative
        # values — read as "no traffic", never negative burn.
        d_total = max(0.0, latest_total - base_total)
        d_good = min(max(0.0, latest_good - base_good), d_total)
        if d_total <= 0:
            return 0.0, 0.0
        bad_ratio = (d_total - d_good) / d_total
        budget = 1.0 - state.objective.target
        return bad_ratio / budget if budget > 0 else 0.0, d_total

    # --- evaluation ---

    def evaluate(self) -> dict:
        """Evaluate every objective over both windows; emit Events +
        audit records on breach transitions. Returns the /slo payload."""
        now = self.clock()
        out = []
        breaches: list[tuple[Objective, float, float]] = []
        with self._lock:
            for state in self._states.values():
                burn_fast, events_fast = self._window_burn(
                    state, now, self.fast_window_s)
                burn_slow, _ = self._window_burn(state, now,
                                                 self.slow_window_s)
                SLO_BURN_RATE.set(round(burn_fast, 4),
                                  objective=state.objective.name,
                                  window="fast")
                SLO_BURN_RATE.set(round(burn_slow, 4),
                                  objective=state.objective.name,
                                  window="slow")
                breached = (events_fast > 0
                            and burn_fast >= self.burn_threshold
                            and burn_slow >= self.burn_threshold)
                if breached and not state.breached:
                    # transition recorded under the lock (exactly one
                    # concurrent evaluator wins); emission happens after
                    breaches.append((state.objective, burn_fast,
                                     burn_slow))
                elif state.breached and not breached:
                    logger.info("SLO %s recovered (burn fast=%.2f "
                                "slow=%.2f)", state.objective.name,
                                burn_fast, burn_slow)
                state.breached = breached
                SLO_BREACHED.set(1.0 if breached else 0.0,
                                 objective=state.objective.name)
                latest = (state.samples[-1] if state.samples
                          else (now, 0.0, 0.0))
                _, good, total = latest
                out.append({
                    **asdict(state.objective),
                    "sli": round(good / total, 6) if total else None,
                    "good_events": good,
                    "total_events": total,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "breached": breached,
                })
        for objective, burn_fast, burn_slow in breaches:
            self._emit_breach(objective, burn_fast, burn_slow)
        self._evaluated_at = time.time()
        return {
            "evaluated_at": round(self._evaluated_at, 3),
            "burn_threshold": self.burn_threshold,
            "windows_s": {"fast": self.fast_window_s,
                          "slow": self.slow_window_s},
            "objectives": out,
        }

    def payload(self) -> dict:
        return self.evaluate()

    # --- breach emission ---

    def _emit_breach(self, objective: Objective, burn_fast: float,
                     burn_slow: float) -> None:
        """One breach transition: counter, audit record (inside a span,
        so the record carries a trace id — the audit trail's invariant),
        and a Kubernetes Event where operators look. Latency breaches
        additionally name the fleet-dominant critical-path phase from
        the assembled recent mount traces (obs/assembly.py), so the
        Event says WHERE the budget is going, not just that it burns."""
        SLO_BREACHES.inc(objective=objective.name)
        message = (
            f"SLO {objective.name} burning error budget at "
            f"{burn_fast:.1f}x (fast window) / {burn_slow:.1f}x (slow "
            f"window), threshold {self.burn_threshold:.1f}x: "
            f"{objective.description or objective.kind}")
        dominant = None
        if objective.kind == "latency":
            from gpumounter_tpu.obs import assembly
            try:
                dominant = assembly.fleet_dominant_phase()
            except Exception:  # noqa: BLE001 — attribution is advisory
                logger.exception("dominant-phase attribution failed")
        if dominant:
            message += (
                f"; fleet-dominant phase: {dominant['phase']} "
                f"({dominant['share']:.0%} of recent mount wall time "
                f"across {dominant['traces']} trace(s))")
        logger.warning("%s", message)
        with trace.span("slo.breach", objective=objective.name):
            AUDIT.record(
                "slo.breach", actor="slo-engine",
                outcome=f"breach: {objective.name}",
                burn_fast=round(burn_fast, 4),
                burn_slow=round(burn_slow, 4),
                target=objective.target,
                **({"dominant_phase": dominant["phase"],
                    "dominant_share": dominant["share"]}
                   if dominant else {}))
            self._post_event(objective, message)

    def _post_event(self, objective: Objective, message: str) -> None:
        from gpumounter_tpu.obs.flight import FLIGHT
        if self.kube is None:
            FLIGHT.record("event", f"TPUSLOBurnRate: {message}"[:240],
                          reason="TPUSLOBurnRate",
                          objective=objective.name, posted=False)
            return
        import secrets
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        namespace = self.cfg.worker_namespace
        manifest = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"tpumounter-slo-{objective.name[:100]}"
                        f".{secrets.token_hex(4)}",
                "namespace": namespace,
            },
            # The master Service is the natural anchor: the breach is a
            # fleet-level condition, not one pod's.
            "involvedObject": {"kind": "Service",
                               "name": "tpumounter-master",
                               "namespace": namespace},
            "reason": "TPUSLOBurnRate",
            "message": message[:1024],
            "type": "Warning",
            "source": {"component": "tpumounter-master"},
            "firstTimestamp": ts,
            "lastTimestamp": ts,
            "count": 1,
        }
        posted = True
        try:
            self.kube.create_event(namespace, manifest)
        except Exception as exc:  # noqa: BLE001 — events are advisory
            posted = False
            logger.warning("SLO breach event post failed: %s", exc)
        FLIGHT.record("event", f"TPUSLOBurnRate: {message}"[:240],
                      reason="TPUSLOBurnRate", objective=objective.name,
                      posted=posted)
