"""Fleet-wide trace assembly and critical-path attribution.

Before this module, spans lived in per-process ring buffers: the master
/trace/<id> route showed only master-local spans and each worker's ops
port only its own. Here the two halves meet:

  * RemoteSpanStore — the master's bounded store of worker-exported
    spans. Workers ship their span rings inside the CollectTelemetry
    snapshot (obs/fleet.py `spans` section, same degradation contract
    as the rest of the telemetry plane: the HTTP-scrape fallback simply
    carries none); the FleetCollector ingests them here, deduplicated
    by span id, so repeated snapshots of a cumulative ring are free.

  * assemble() — joins master-local spans (the process tracer ring)
    with federated remote spans by trace id into an end-to-end
    operation tree, flags incompleteness (orphan spans whose parent
    never arrived; rpc client spans missing their worker half), and
    attributes every instant of the operation's wall time to exactly
    one PHASE — admission gate, shard proxy hop, k8s API wait,
    slave-pod scheduling, cgroup grant, mknod fan-out, verify, RPC
    transport — by walking the tree's wall-clock intervals (a child's
    window is charged to the child's phase; uncovered time to the
    owning span's own phase; overlap between parallel siblings — the
    mknod fan-out — is charged once, to the earliest sibling). By
    construction the per-phase attribution sums to the root span's
    wall time, which is exactly what chaos invariant 16 asserts.

  * fleet_dominant_phase() — the same attribution aggregated over the
    most recent mount-shaped edge spans, so the SLO engine can stamp
    WHERE the latency budget is going into a TPUSLOBurnRate breach
    Event instead of just that it is burning.

Stdlib-only (lazy-grpc policy: imported by worker and master alike).
"""

from __future__ import annotations

from collections import OrderedDict

from gpumounter_tpu.obs import trace
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("obs.assembly")

REMOTE_SPANS_INGESTED = REGISTRY.counter(
    "tpumounter_remote_spans_ingested_total",
    "Worker spans newly federated into the master's remote-span store "
    "(re-sent spans dedupe by span id and are not counted)")
REMOTE_SPAN_EVICTIONS = REGISTRY.counter(
    "tpumounter_remote_span_evictions_total",
    "Federated worker spans dropped from the remote-span store by "
    "capacity pressure (raise TPUMOUNTER_REMOTE_SPAN_CAPACITY)")

#: span-name -> phase taxonomy, FIRST matching prefix wins (so the
#: specific http.admission outranks the http. edge catch-all). These
#: are the phases a hot mount/unmount/migration actually pays; an
#: unknown span name falls back to its first dotted segment so new
#: subsystems degrade to a readable bucket instead of "other".
PHASES: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("admission", ("http.admission",)),
    ("shard_proxy", ("proxy.",)),
    ("k8s_api", ("k8s.",)),
    ("slave_pod_schedule", ("mount.slave_pod_schedule",)),
    ("cgroup_grant", ("mount.cgroup_grant", "unmount.cgroup_revoke")),
    ("verify", ("mount.verify",)),
    ("mknod", ("mount.mknod", "unmount.device_remove")),
    ("rollback", ("mount.rollback",)),
    ("worker", ("worker.",)),
    ("rpc", ("rpc.",)),
    ("migrate", ("migrate.",)),
    ("edge", ("http.", "chaos.", "slice.", "bulk.", "elastic.")),
)

#: rpc client spans whose worker half is read-only scrape noise the
#: worker deliberately defers-and-drops — their absence is not
#: incomplete assembly.
_RPC_NO_WORKER_HALF = frozenset({"rpc.CollectTelemetry"})


def phase_of(name: str) -> str:
    for phase, prefixes in PHASES:
        for prefix in prefixes:
            if name.startswith(prefix):
                return phase
    return name.split(".", 1)[0] if name else "unknown"


class RemoteSpanStore:
    """Bounded master-side store of federated worker spans.

    Keyed by span id (workers re-send their whole ring each telemetry
    pass — dedup makes that free) with a per-trace index for O(1)
    /trace joins. FIFO eviction by ingest order: the store is a join
    buffer, not an archive — the JSONL sinks are the archive.
    """

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._lock = OrderedLock("assembly.remote")
        self._spans: OrderedDict[str, dict] = OrderedDict()
        self._by_trace: dict[str, set[str]] = {}

    def ingest(self, node: str, spans) -> int:
        """Store every not-yet-seen span, stamped with the node it came
        from. Returns how many were new. Malformed entries (a hostile
        or buggy peer's payload) are skipped, never raised."""
        if not isinstance(spans, (list, tuple)):
            return 0
        new = 0
        evicted = 0
        with self._lock:
            for span in spans:
                if not isinstance(span, dict):
                    continue
                sid = span.get("span_id")
                tid = span.get("trace_id")
                if not sid or not tid or not isinstance(sid, str) \
                        or not isinstance(tid, str):
                    continue
                if sid in self._spans:
                    continue
                entry = dict(span)
                entry["node"] = node
                self._spans[sid] = entry
                self._by_trace.setdefault(tid, set()).add(sid)
                new += 1
            while len(self._spans) > max(1, self.capacity):
                old_sid, old = self._spans.popitem(last=False)
                ids = self._by_trace.get(old.get("trace_id", ""))
                if ids is not None:
                    ids.discard(old_sid)
                    if not ids:
                        self._by_trace.pop(old.get("trace_id", ""), None)
                evicted += 1
        if new:
            REMOTE_SPANS_INGESTED.inc(float(new))
        if evicted:
            REMOTE_SPAN_EVICTIONS.inc(float(evicted))
        return new

    def spans_for(self, trace_id: str) -> list[dict]:
        with self._lock:
            ids = self._by_trace.get(trace_id) or ()
            return [dict(self._spans[sid]) for sid in ids
                    if sid in self._spans]

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._spans.values()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._by_trace.clear()


REMOTE_SPANS = RemoteSpanStore()


def configure(cfg) -> None:
    """Daemon-startup wiring: remote-store capacity from config."""
    REMOTE_SPANS.capacity = cfg.remote_span_capacity


# --- assembly ---


def _attribute(span: dict, children: dict[str, list[dict]],
               lo: float, hi: float, acc: dict[str, float]) -> None:
    """Attribute the wall-clock window [lo, hi] owned by `span`:
    uncovered time to the span's own phase, covered time recursively to
    the covering child. Siblings are walked in start order and a later
    sibling's overlap with an earlier one is skipped, so every instant
    is charged exactly once and sum(acc) == hi - lo by construction.
    Child windows are clipped to the parent's (cross-process wall
    clocks drift; clipping keeps the books exact anyway)."""
    phase = phase_of(span.get("name", ""))
    cursor = lo
    kids = sorted(children.get(span.get("span_id", ""), []),
                  key=lambda s: s.get("start", 0.0))
    for kid in kids:
        k_lo = max(cursor, float(kid.get("start", 0.0)))
        k_hi = min(hi, float(kid.get("start", 0.0))
                   + float(kid.get("duration_s", 0.0)))
        if k_hi <= cursor:
            continue  # fully inside an earlier sibling's window
        if k_lo > cursor:
            acc[phase] = acc.get(phase, 0.0) + (min(k_lo, hi) - cursor)
        if k_lo >= hi:
            break
        _attribute(kid, children, k_lo, k_hi, acc)
        cursor = k_hi
    if cursor < hi:
        acc[phase] = acc.get(phase, 0.0) + (hi - cursor)


def _waterfall(roots: list[dict], children: dict[str, list[dict]],
               origin: float) -> list[dict]:
    out: list[dict] = []

    def walk(span: dict, depth: int) -> None:
        entry = dict(span)
        entry["depth"] = depth
        entry["offset_ms"] = round(
            (float(span.get("start", origin)) - origin) * 1000.0, 3)
        entry["phase"] = phase_of(span.get("name", ""))
        out.append(entry)
        for kid in sorted(children.get(span.get("span_id", ""), []),
                          key=lambda s: s.get("start", 0.0)):
            walk(kid, depth + 1)

    for root in sorted(roots, key=lambda s: s.get("start", 0.0)):
        walk(root, 0)
    return out


def assemble(trace_id: str, tracer=None, remote=None) -> dict | None:
    """One trace's end-to-end story, across daemons.

    Joins the local tracer ring with the federated remote-span store
    (local wins a span-id collision — its view has no federation lag),
    builds the operation tree, and attributes wall time to phases.
    Returns None when NOTHING is buffered for the id (expired, or
    minted elsewhere); otherwise a payload that also says how complete
    the assembly is — `orphans` (spans whose parent never arrived) and
    `missing_worker_halves` (successful rpc.* client spans with no
    worker-side child yet) are the two ways a distributed trace lies.
    """
    local = (tracer or trace.TRACER).ring.spans_for(trace_id)
    remote_store = REMOTE_SPANS if remote is None else remote
    merged: dict[str, dict] = {}
    for span in remote_store.spans_for(trace_id):
        sid = span.get("span_id")
        if sid:
            merged[sid] = span
    for span in local:
        sid = span.get("span_id")
        if not sid:
            continue
        prior = merged.get(sid)
        # keep the remote copy's node stamp when the same span is seen
        # from both sides (single-process test stacks)
        merged[sid] = {**(prior or {}), **span}
    if not merged:
        return None

    spans = sorted(merged.values(),
                   key=lambda s: (s.get("start", 0.0),
                                  s.get("span_id", "")))
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    orphans: list[dict] = []
    for span in spans:
        parent_id = span.get("parent_id") or ""
        if not parent_id:
            roots.append(span)
        elif parent_id in merged:
            children.setdefault(parent_id, []).append(span)
        else:
            orphans.append(span)

    missing_halves: list[str] = []
    for span in spans:
        name = span.get("name", "")
        if not name.startswith("rpc.") or name in _RPC_NO_WORKER_HALF:
            continue
        if span.get("status") != "ok":
            continue  # the RPC died — there may honestly be no worker half
        kids = children.get(span.get("span_id", ""), [])
        if not any(k.get("name", "").startswith("worker.") for k in kids):
            missing_halves.append(span.get("span_id", ""))

    phases: dict[str, float] = {}
    wall_s = 0.0
    primary = None
    for root in roots:
        lo = float(root.get("start", 0.0))
        hi = lo + float(root.get("duration_s", 0.0))
        _attribute(root, children, lo, hi, phases)
        wall_s += float(root.get("duration_s", 0.0))
        if primary is None or root.get("duration_s", 0.0) > \
                primary.get("duration_s", 0.0):
            primary = root
    # an orphans-only trace (local half expired) still renders: the
    # orphan subtrees become the waterfall, but assembly is incomplete.
    origin = spans[0].get("start", 0.0)

    phase_ms = {p: round(s * 1000.0, 3) for p, s in phases.items()}
    total_ms = sum(phase_ms.values())
    critical_path = sorted(
        ({"phase": p, "ms": ms,
          "share": round(ms / total_ms, 4) if total_ms else 0.0}
         for p, ms in phase_ms.items()),
        key=lambda e: -e["ms"])
    dominant = critical_path[0] if critical_path else None

    return {
        "trace": trace_id,
        "op": (primary or {}).get("name", ""),
        "wall_ms": round(wall_s * 1000.0, 3),
        "spans": _waterfall(roots + orphans, children, origin),
        "roots": len(roots),
        "nodes": sorted({s.get("node", "") for s in spans
                         if s.get("node")}),
        "phases": phase_ms,
        "critical_path": critical_path,
        "dominant": dominant,
        "complete": not orphans and not missing_halves,
        "orphans": [s.get("span_id", "") for s in orphans],
        "missing_worker_halves": missing_halves,
    }


#: edge span names whose traces describe mount-shaped operations — the
#: population fleet_dominant_phase() aggregates over.
MOUNT_EDGE_PREFIXES = ("http.add", "http.batch_add", "http.remove",
                       "chaos.", "worker.AddTPU", "worker.RemoveTPU")


def fleet_dominant_phase(tracer=None, remote=None,
                         limit: int = 32) -> dict | None:
    """Aggregate per-phase attribution over the newest `limit`
    mount-shaped traces and name the dominant phase — the SLO engine's
    'where is the latency going' stamp for burn-rate breach Events.
    Worker-edge spans only count as population roots when the master's
    http edge is absent (a worker process evaluating locally)."""
    ring = (tracer or trace.TRACER).ring.snapshot()
    trace_ids: list[str] = []
    for span in reversed(ring):
        if span.get("parent_id"):
            continue
        name = span.get("name", "")
        if not any(name.startswith(p) for p in MOUNT_EDGE_PREFIXES):
            continue
        tid = span.get("trace_id", "")
        if tid and tid not in trace_ids:
            trace_ids.append(tid)
        if len(trace_ids) >= limit:
            break
    if not trace_ids:
        return None
    acc: dict[str, float] = {}
    assembled = 0
    for tid in trace_ids:
        tree = assemble(tid, tracer=tracer, remote=remote)
        if tree is None:
            continue
        assembled += 1
        for phase, ms in tree["phases"].items():
            acc[phase] = acc.get(phase, 0.0) + ms
    if not acc:
        return None
    total = sum(acc.values())
    dominant = max(acc, key=lambda p: acc[p])
    return {
        "phase": dominant,
        "ms": round(acc[dominant], 3),
        "share": round(acc[dominant] / total, 4) if total else 0.0,
        "traces": assembled,
    }
