"""Append-only audit trail for every mutating control-plane operation.

Each record answers, without log spelunking: who asked (actor), what
moved (operation, pod, chips, idempotency key), how it ended (outcome),
how long it took, and which trace tells the full story (trace id — the
join key into obs.trace and the structured logs).

The `audited()` context manager is the writing discipline: the record
is emitted in a finally block, so every operation — including one died
by an injected CrashError mid-phase — leaves a terminal record. The
chaos harness asserts exactly that (testing/chaos.py invariant 5/6:
terminal audit records, no orphan open spans).

Storage is a bounded in-memory ring (the master /audit route and the
`tpumounter audit` CLI read it) plus an optional append-only JSONL file
for durability across restarts. Stdlib-only (lazy-grpc policy).
"""

from __future__ import annotations

import contextlib
import itertools
import time
from collections import deque

from gpumounter_tpu.obs import trace
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("obs.audit")


class AuditLog:
    """Thread-safe bounded append-only record store."""

    def __init__(self, capacity: int = 4096):
        from gpumounter_tpu.obs.sinks import JsonlSink
        self._records: deque[dict] = deque(maxlen=capacity)
        self._lock = OrderedLock("audit.records")
        self._seq = itertools.count(1)
        self._jsonl = JsonlSink("audit")
        # Record subscribers (the flight recorder's timeline feed):
        # called outside the lock, exceptions logged and swallowed —
        # a broken observer must never fail the mutation being audited.
        self._subscribers: list = []

    def subscribe(self, fn) -> None:
        """fn(record) after every append. Idempotent by identity, so a
        process-global hook can re-install itself freely."""
        with self._lock:
            if not any(s is fn for s in self._subscribers):
                self._subscribers.append(fn)

    def configure_jsonl(self, path: str) -> None:
        self._jsonl.configure(path)

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._records = deque(self._records, maxlen=max(1, capacity))

    def record(self, operation: str, actor: str = "", namespace: str = "",
               pod: str = "", chips: list[str] | tuple | None = None,
               idempotency_key: str = "", outcome: str = "",
               duration_s: float = 0.0, trace_id: str | None = None,
               **details) -> dict:
        """Append one record. trace_id defaults to the ambient one —
        callers inside a span need not thread it through."""
        rec = {
            "seq": next(self._seq),
            "at": round(time.time(), 3),
            "operation": operation,
            "actor": actor,
            "namespace": namespace,
            "pod": pod,
            "chips": sorted(chips) if chips else [],
            "idempotency_key": idempotency_key,
            "outcome": outcome,
            "duration_s": round(duration_s, 6),
            "trace_id": trace.current_trace_id()
            if trace_id is None else trace_id,
        }
        if details:
            rec["details"] = {k: v for k, v in details.items()}
        with self._lock:
            self._records.append(rec)
            subscribers = list(self._subscribers)
        self._jsonl.write(rec)
        for fn in subscribers:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — observers never fail the op
                logger.exception("audit subscriber failed")
        return rec

    def query(self, operation: str | None = None,
              namespace: str | None = None, pod: str | None = None,
              trace_id: str | None = None, outcome: str | None = None,
              limit: int = 100) -> list[dict]:
        """Newest-first filtered view. `operation` and `outcome` match
        as prefixes (op="worker." or outcome="error" sweep a family)."""
        with self._lock:
            records = list(self._records)
        out = []
        for rec in reversed(records):
            if operation and not rec["operation"].startswith(operation):
                continue
            if namespace and rec["namespace"] != namespace:
                continue
            if pod and rec["pod"] != pod:
                continue
            if trace_id and rec["trace_id"] != trace_id:
                continue
            if outcome and not rec["outcome"].startswith(outcome):
                continue
            out.append(dict(rec))
            if len(out) >= max(1, limit):
                break
        return out

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._records]

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._jsonl.configure("")


AUDIT = AuditLog()


def query_from_params(params: dict[str, list[str]],
                      log: AuditLog | None = None) -> dict:
    """The /audit query contract, shared by the master route and the
    worker ops port so the two daemons cannot drift: last-value-wins
    params `namespace`/`pod`/`op`/`trace`/`outcome`/`limit` (default
    100). Raises ValueError on a non-integer limit."""

    def _one(key: str) -> str | None:
        values = params.get(key)
        return values[-1] if values else None

    limit = int(_one("limit") or 100)
    sink = log or AUDIT
    return {"records": sink.query(
        operation=_one("op"), namespace=_one("namespace"),
        pod=_one("pod"), trace_id=_one("trace"),
        outcome=_one("outcome"), limit=limit)}


def configure(cfg) -> None:
    """Daemon-startup wiring (master/worker main): record capacity and
    the optional JSONL sink from config."""
    AUDIT.set_capacity(cfg.audit_capacity)
    AUDIT.configure_jsonl(cfg.audit_jsonl)


@contextlib.contextmanager
def audited(operation: str, actor: str = "", namespace: str = "",
            pod: str = "", chips: list[str] | None = None,
            idempotency_key: str = "", log: AuditLog | None = None,
            **details):
    """Wrap one mutating operation; ALWAYS writes a terminal record.

    Yields a mutable dict the body may enrich ("outcome", "chips",
    "details"). An unhandled exception (CrashError included) records
    `error: <type>: <msg>` as the outcome and re-raises.
    """
    sink = log or AUDIT
    holder: dict = {"chips": list(chips or []), "details": dict(details)}
    t0 = time.monotonic()
    try:
        yield holder
        holder.setdefault("outcome", "success")
    except BaseException as exc:
        # setdefault: a body that already classified the failure (the
        # HTTP edge recording the mapped status) wins over the generic
        # error string.
        holder.setdefault("outcome", f"error: {type(exc).__name__}: {exc}")
        raise
    finally:
        sink.record(
            operation, actor=actor, namespace=namespace, pod=pod,
            chips=holder.get("chips"),
            idempotency_key=idempotency_key,
            outcome=holder.get("outcome", "error: abandoned"),
            duration_s=time.monotonic() - t0,
            **holder.get("details", {}))
