"""Contextvar-based distributed tracing for the hot-mount control plane.

A trace id is minted once, at the master HTTP edge (master/app.py
stamps it on the response as the X-Tpumounter-Trace header), and flows

  HTTP route span -> rpc.<Method> client span -> [wire: trace_context
  field on the request message] -> worker.<Method> span -> mount-phase
  spans (cgroup grant, mknod, rollback, journal writes)

so one id strings together everything an operation touched on both
daemons. The wire carrier is a plain `<trace_id>-<span_id>` string in a
proto3 field legacy peers skip (rpc/api.py) — a reference worker simply
drops it, and garbage from a hostile/buggy peer parses to None (the
span then starts a fresh trace rather than failing the RPC).

Spans nest through a contextvar: `span()` makes the new span current
for its body, children parent to it automatically, and threads that
must carry a context across an explicit boundary (slice fan-out, the
migration machine's per-migration thread) capture `current()` and enter
`attached(ctx)`.

Exporters: every finished span goes to an in-memory ring buffer (the
master /trace/<id> route and the worker ops port serve it) and, when
configured, an append-only JSONL file. Open spans are tracked so the
chaos harness can assert none leak — a span closes even on an injected
CrashError because the context manager's finally always runs.

Stdlib-only (lazy-grpc policy: this is imported by the mount path).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import re
import secrets
import time
from collections import deque
from dataclasses import dataclass

from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("obs.trace")

TRACE_RING_EVICTIONS = REGISTRY.counter(
    "tpumounter_trace_ring_evictions_total",
    "Finished spans rotated out of the in-memory ring by capacity "
    "pressure — silent trace loss that an incident review would hit "
    "(raise TPUMOUNTER_TRACE_RING or add a JSONL sink when it grows)")

#: HTTP header carrying a wire context: accepted on requests at the
#: master edge (CLI/test continuity), stamped on every routed response
#: with the trace id the operation ran under.
TRACE_HEADER = "x-tpumounter-trace"
RESPONSE_HEADER = "X-Tpumounter-Trace"

_WIRE_RE = re.compile(r"^([0-9a-f]{16,32})-([0-9a-f]{8,16})$")


@dataclass(frozen=True)
class TraceContext:
    """The ambient (trace id, span id) pair a new span parents to."""

    trace_id: str
    span_id: str = ""

    def to_wire(self) -> str:
        return f"{self.trace_id}-{self.span_id or _new_span_id()}"


def new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


def parse_wire_context(raw: object) -> TraceContext | None:
    """Tolerant wire-context parse: absent (empty/None), wrong-typed,
    or malformed input — anything a legacy or buggy peer could send —
    yields None, never an exception. The caller then starts a fresh
    trace instead of failing the operation."""
    if not raw or not isinstance(raw, str):
        return None
    match = _WIRE_RE.match(raw.strip())
    if match is None:
        return None
    return TraceContext(match.group(1), match.group(2))


_current: contextvars.ContextVar[TraceContext | None] = \
    contextvars.ContextVar("tpumounter_trace", default=None)

#: when set, finished spans in this context buffer here instead of
#: exporting — see deferred().
_deferred: contextvars.ContextVar["_DeferredSpans | None"] = \
    contextvars.ContextVar("tpumounter_trace_deferred", default=None)

#: the innermost open span's mutable attribute dict — set_attrs()
#: writes through it for outcomes only known mid-span (a mount's
#: warm-pool hit/gap is decided by the allocator, inside the
#: already-open slave_pod_schedule span).
_span_attrs: contextvars.ContextVar[dict | None] = \
    contextvars.ContextVar("tpumounter_trace_attrs", default=None)


def set_attrs(**attrs) -> None:
    """Attach attributes to the innermost open span of THIS context.
    No-op when no span is open — call sites need no conditional, and a
    background thread without an attached context simply records
    nothing. Attributes land when the span closes (same export record
    as open-time attrs; later writes to the same key win)."""
    current_attrs = _span_attrs.get()
    if current_attrs is not None:
        current_attrs.update(attrs)


def current() -> TraceContext | None:
    """The ambient context (for explicit cross-thread handoff)."""
    return _current.get()


def current_trace_id() -> str:
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else ""


def wire_context() -> str:
    """Serialized ambient context for the RPC wire ("" when untraced —
    proto3 omits the empty string, so an untraced call is byte-identical
    to a legacy client's)."""
    ctx = _current.get()
    return ctx.to_wire() if ctx is not None else ""


class RingBufferExporter:
    """Last-N finished spans, queryable by trace id (served by the
    master /trace/<id> route and the worker ops port)."""

    def __init__(self, capacity: int = 2048):
        self._spans: deque[dict] = deque(maxlen=capacity)
        self._lock = OrderedLock("trace.ring")

    def export(self, span: dict) -> None:
        with self._lock:
            evicting = (self._spans.maxlen is not None
                        and len(self._spans) >= self._spans.maxlen)
            self._spans.append(span)
        if evicting:
            TRACE_RING_EVICTIONS.inc()

    def spans_for(self, trace_id: str) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._spans
                    if s.get("trace_id") == trace_id]

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._spans]

    def tail(self, n: int) -> list[dict]:
        """Newest n spans, copying ONLY those n under the lock — the
        span-export path calls this every telemetry pass, and copying
        the whole ring to keep a quarter of it would contend with the
        hot mount path's exports for nothing."""
        if n <= 0:
            return []
        with self._lock:
            start = max(0, len(self._spans) - n)
            return [dict(s) for s in
                    itertools.islice(self._spans, start, None)]

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._spans = deque(self._spans, maxlen=max(1, capacity))

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


class JsonlExporter:
    """Append-only JSONL sink (one span per line), on the shared
    self-disabling spill discipline (obs/sinks.py) — tracing must never
    take down a mount because a disk filled."""

    def __init__(self, path: str):
        from gpumounter_tpu.obs.sinks import JsonlSink
        self.path = path
        self._lock = OrderedLock("trace.jsonl")
        self._sink = JsonlSink("trace", path)

    def export(self, span: dict) -> None:
        with self._lock:
            self._sink.write(span)


class Tracer:
    """Exporter fan-out + open-span accounting. One global instance
    (module-level `span()`/`TRACER`); tests may build private ones."""

    def __init__(self, ring_capacity: int = 2048):
        self.ring = RingBufferExporter(ring_capacity)
        self._exporters: list = [self.ring]
        self._lock = OrderedLock("trace.tracer")
        self._open: dict[str, str] = {}  # span_id -> name

    def add_exporter(self, exporter) -> None:
        """Idempotent by identity: process-global exporters (the flight
        recorder) re-install themselves after a test reset without ever
        double-exporting."""
        with self._lock:
            if not any(e is exporter for e in self._exporters):
                self._exporters.append(exporter)

    def configure_jsonl(self, path: str) -> None:
        if path:
            self.add_exporter(JsonlExporter(path))

    def export(self, span: dict) -> None:
        with self._lock:
            exporters = list(self._exporters)
        for exporter in exporters:
            try:
                exporter.export(span)
            except Exception as exc:  # noqa: BLE001 — never fail the op
                logger.error("span exporter %r failed: %s", exporter, exc)

    # --- open-span accounting (chaos invariant: none leak) ---

    def _open_add(self, span_id: str, name: str) -> None:
        with self._lock:
            self._open[span_id] = name

    def _open_remove(self, span_id: str) -> None:
        with self._lock:
            self._open.pop(span_id, None)

    def open_spans(self) -> list[str]:
        """Names of spans entered but not yet exited."""
        with self._lock:
            return sorted(self._open.values())

    def reset(self) -> None:
        """Test hook: drop buffered spans, open-span records, and any
        configured extra exporters (the ring stays)."""
        with self._lock:
            self._exporters = [self.ring]
            self._open.clear()
        self.ring.clear()


TRACER = Tracer()


def configure(cfg) -> None:
    """Daemon-startup wiring (master/worker main): ring capacity and
    the optional JSONL sink from config."""
    TRACER.ring.set_capacity(cfg.trace_ring_capacity)
    TRACER.configure_jsonl(cfg.trace_jsonl)


def trace_payload(trace_id: str, tracer: Tracer | None = None) -> dict | None:
    """The /trace/<id> response contract, shared by the master route
    and the worker ops port: buffered spans for one trace sorted by
    start time, or None when the ring holds nothing for the id."""
    spans = (tracer or TRACER).ring.spans_for(trace_id)
    if not spans:
        return None
    spans.sort(key=lambda s: s.get("start", 0.0))
    return {"trace": trace_id, "spans": spans}


@contextlib.contextmanager
def span(name: str, wire_parent: str | None = None,
         tracer: Tracer | None = None, **attrs):
    """One traced operation phase. Yields the span's TraceContext
    (children opened in the body parent to it via the contextvar).

    Parent resolution, in order:
      1. the ambient contextvar (nested span),
      2. `wire_parent` — a serialized context off the wire (HTTP header
         or rpc trace_context field); malformed/absent input is ignored,
      3. none: a fresh trace id is minted (background loops like the
         elastic reconciler start their own traces).
    """
    t = tracer or TRACER
    parent = _current.get()
    remote = parse_wire_context(wire_parent) if wire_parent else None
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    elif remote is not None:
        trace_id, parent_id = remote.trace_id, remote.span_id
    else:
        trace_id, parent_id = new_trace_id(), ""
    span_id = _new_span_id()
    ctx = TraceContext(trace_id, span_id)
    token = _current.set(ctx)
    mutable_attrs = dict(attrs)
    attrs_token = _span_attrs.set(mutable_attrs)
    t._open_add(span_id, name)
    started_at = time.time()
    t0 = time.monotonic()
    status, error = "ok", ""
    try:
        yield ctx
    except BaseException as exc:
        status, error = "error", f"{type(exc).__name__}: {exc}"
        raise
    finally:
        _current.reset(token)
        _span_attrs.reset(attrs_token)
        t._open_remove(span_id)
        record = {
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "name": name,
            "start": round(started_at, 6),
            "duration_s": round(time.monotonic() - t0, 6),
            "status": status,
        }
        if error:
            record["error"] = error
        if mutable_attrs:
            record["attrs"] = dict(mutable_attrs)
        pending = _deferred.get()
        if pending is not None:
            pending.append(record)
        else:
            t.export(record)


class _DeferredSpans:
    """Spans buffered by a deferred() block; publish() exports them."""

    def __init__(self, tracer: Tracer):
        self._tracer = tracer
        self._spans: list[dict] = []
        self._published = False

    def append(self, span: dict) -> None:
        if self._published:  # late closer after an early publish()
            self._tracer.export(span)
        else:
            self._spans.append(span)

    def publish(self) -> None:
        if self._published:
            return
        self._published = True
        for span in self._spans:
            self._tracer.export(span)
        self._spans = []


@contextlib.contextmanager
def deferred(tracer: Tracer | None = None):
    """Buffer this context's spans; the caller decides afterwards to
    publish() or drop them. For high-frequency control loops (the
    elastic resync) whose no-op passes would otherwise rotate real
    operation traces out of the ring — trace everything, keep only the
    passes that did something. Spans in OTHER threads (slice fan-out
    workers) export directly as usual; only this context buffers."""
    pending = _DeferredSpans(tracer or TRACER)
    token = _deferred.set(pending)
    try:
        yield pending
    finally:
        _deferred.reset(token)


@contextlib.contextmanager
def attached(ctx: TraceContext | None):
    """Re-attach a captured context in another thread (slice fan-out
    workers, the migration machine's thread). No-op for None, so call
    sites need no conditional."""
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)
