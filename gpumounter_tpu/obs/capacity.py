"""Capacity & fragmentation observability plane.

The ROADMAP's two biggest open levers — the ICI defragmenter and the
autoscaling loop — both start from a signal the system could not
produce before this module: "how many free chips does the fleet have,
in what ICI shapes, and what's the probability a v5litepod-16 intent
admits right now?" `allocator/placement.py` scores per-host contiguity
and `master/topology.py` knows real slice geometries, but that
knowledge was consumed transiently at mount time and never observed.
This module makes capacity, fragmentation and headroom first-class
observable state BEFORE any controller acts on them:

  * node_capacity_snapshot() — the worker half: per-host chip inventory
    (free / held / warm-pool / fenced, WITH chip indices so contiguity
    is computable fleet-side) riding the CollectTelemetry snapshot.
    The HTTP-scrape fallback degrades like the rest of the telemetry
    plane: the classic exposition cannot carry indices, so a legacy
    worker's node simply reports no capacity section.

  * host_capacity() — per-host derived view: an ICI fragmentation
    index (1 - largest-achievable-contiguous-block / free chips; 0 =
    every free chip reachable in one ICI-connected block, -> 1 =
    scattered), the largest achievable block, and which per-host block
    sizes (1/2/4/8 — the chips-per-host vocabulary of every published
    slice shape) are admissible right now. Achievability is exact:
    a contiguous block of size k exists iff the free set has an
    ICI-connected component of >= k chips (any connected subgraph
    prefix of a BFS tree realises it); placement.best_block then names
    the concrete chips a mount would take.

  * CapacityPlane — the master half: rolls every node's reported
    inventory into (a) per-host and fleet fragmentation indices, (b) a
    per-size allocation-feasibility table for every master/topology.py
    accelerator type (admissible now / admissible-after-defrag /
    infeasible, with the blocking hosts named), and (c) a headroom
    forecast joining the /tenants queue-depth and tokens/sec signals
    against free capacity. Served at GET /capacity (read scope,
    per-shard collection federated exactly like /fleet — the rollup is
    derived from the same FleetCollector pass), consumed by the
    `tpumounter capacity` verb, and sampled into the slice-feasibility
    SLO objective (obs/slo.py) via two cumulative counters.

  * record_rejection() — rejected-for-capacity admissions stamp the
    feasibility verdict into the audit trail (and, via the audit
    subscriber, the incident flight recorder's timeline) so an
    incident review sees WHY an intent couldn't place, not just that
    it didn't.

Chip indices ride the JSON plane only — never metric labels (the
cardinality guard in tests/test_metrics_cardinality.py asserts this).
Stdlib-only (lazy-grpc policy: the worker imports the snapshot half on
its telemetry path).
"""

from __future__ import annotations

import time
from collections import deque

from gpumounter_tpu.allocator import placement
from gpumounter_tpu.obs.audit import AUDIT
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("obs.capacity")

CAPACITY_SCHEMA = "tpumounter-capacity/1"

#: the chips-per-host vocabulary of every published slice shape
#: (master/topology.py): v5e hosts carry 1/2/4/8 chips, v4/v5p always 4.
HOST_BLOCK_SIZES = (1, 2, 4, 8)

# Fleet-level gauges only: per-node numbers ride the JSON plane (the
# /capacity payload), never node-labeled series — same cardinality
# discipline as the rest of the fleet plane.
CAPACITY_FREE_CHIPS = REGISTRY.gauge(
    "tpumounter_capacity_free_chips",
    "Free (healthy, unbooked) chips across the last capacity rollup")
CAPACITY_FRAG_INDEX = REGISTRY.gauge(
    "tpumounter_capacity_fragmentation_index",
    "Fleet ICI fragmentation index: 1 - achievable-contiguous / free "
    "(0 = perfectly defragmented, -> 1 = scattered)")
CAPACITY_SIZE_FEASIBLE = REGISTRY.counter(
    "tpumounter_capacity_size_feasible_total",
    "Per-collection-pass accelerator-size feasibility evaluations NOT "
    "denied by fragmentation: admissible now, or out of reach for raw "
    "free capacity (utilization — capacity planning's problem, not a "
    "page). The slice-feasibility SLO's good events")
CAPACITY_SIZE_INFEASIBLE = REGISTRY.counter(
    "tpumounter_capacity_size_infeasible_total",
    "Per-collection-pass accelerator-size feasibility evaluations "
    "where the free chips EXIST but ICI fragmentation denies placement "
    "(admissible-after-defrag) — the slice-feasibility SLO's bad "
    "events and the defragmenter's work signal")


# --- worker half: the per-host inventory snapshot ---


def node_capacity_snapshot(collector, pool=None, cfg=None) -> dict:
    """This worker's chip inventory, classified free / held / warm /
    fenced with indices — the `capacity` section of the CollectTelemetry
    snapshot. Classification priority: an unhealthy chip is fenced no
    matter who books it (a dead chip is capacity to nobody); a healthy
    booked chip is warm when its holder is a warm-pool pod, held
    otherwise; everything else is free. Ownership refresh degrades like
    the collector always has (a kubelet blip keeps the old marks and
    flips ownership_known, it never fails the telemetry pass)."""
    if cfg is None:
        from gpumounter_tpu.config import get_config
        cfg = get_config()
    collector.update_status()
    devices = collector.snapshot()
    node = getattr(cfg, "node_name", "") or ""
    ready: set[str] = set()
    if pool is not None and getattr(pool, "enabled", False):
        ready = set(pool.ready_names(node))
    free: list[int] = []
    warm: list[int] = []
    fenced: list[int] = []
    held: dict[str, str] = {}
    for dev in devices:
        healthy, _reason = collector.backend.probe_device(dev)
        if not healthy:
            fenced.append(dev.index)
            continue
        if not dev.pod_name:
            free.append(dev.index)
            continue
        if dev.namespace == cfg.pool_namespace and dev.pod_name in ready:
            # ONLY the pool's ready book decides warm — never the
            # warm-slave- name prefix: adopted holders keep their names
            # (pods cannot be renamed; ownership moves by label), so a
            # prefix match would count a tenant's chips as reclaimable
            # forever. The book survives restarts via ensure_node's
            # resync; with no pool, leftover holders read held, which
            # is the conservative truth (nobody will adopt them).
            warm.append(dev.index)
        else:
            held[str(dev.index)] = f"{dev.namespace}/{dev.pod_name}"
    return {
        "schema": CAPACITY_SCHEMA,
        "total": len(devices),
        "free": sorted(free),
        "warm": sorted(warm),
        "fenced": sorted(fenced),
        "held": {k: held[k] for k in sorted(held, key=int)},
        # The pool's own ready book, so /capacity warm coverage and the
        # tpumounter_warm_pool_ready gauge describe the same number.
        "warm_ready": (pool.ready_count(node)
                       if pool is not None and node else len(warm)),
        "ownership_known": bool(getattr(collector, "ownership_known",
                                        True)),
    }


# --- per-host derived view ---


def largest_ici_block(free: list[int]) -> int:
    """Size of the largest ICI-connected component of the free set —
    the largest contiguous block any single mount on this host could
    get. Exact: a connected subgraph of any size up to the component
    size always exists (a BFS-tree prefix realises it).

    On the 2-wide row-major grid (placement.chip_coord) a chip's ICI
    neighbors are exactly {i^1, i-2, i+2} — i^1 flips x within the
    tray row, ±2 steps y — so components fall out of an O(n) BFS with
    constant-time neighbor lookups (this runs per host per collection
    pass; the collect-overhead budget is 5%)."""
    pending = set(free)
    best = 0
    while pending:
        seed = pending.pop()
        component = 1
        frontier = [seed]
        while frontier:
            chip = frontier.pop()
            for nbr in (chip ^ 1, chip - 2, chip + 2):
                if nbr in pending:
                    pending.discard(nbr)
                    component += 1
                    frontier.append(nbr)
        best = max(best, component)
    return best


def host_capacity(snapshot: dict | None) -> dict:
    """One node's derived capacity view from its reported inventory.
    None (legacy worker / scrape fallback) yields capacity_unknown —
    the fleet rollup excludes the node from feasibility math instead of
    treating it as empty. The best_block search is the expensive part;
    the plane's inventory-keyed cache (CapacityPlane._derive_hosts)
    runs this only for hosts whose chips actually moved, which is how
    a whole-fleet pass stays inside the collect-overhead budget
    (bench_capacity.py gates 5%)."""
    if not isinstance(snapshot, dict):
        return {"capacity_unknown": True}
    free = sorted(int(i) for i in snapshot.get("free") or [])
    warm = sorted(int(i) for i in snapshot.get("warm") or [])
    fenced = sorted(int(i) for i in snapshot.get("fenced") or [])
    held = snapshot.get("held") or {}
    largest = largest_ici_block(free)
    n_free = len(free)
    entry = {
        "total": int(snapshot.get("total", 0)),
        "free": n_free,
        "held": len(held),
        "warm": len(warm),
        "fenced": len(fenced),
        "free_indices": free,
        "warm_ready": int(snapshot.get("warm_ready", len(warm))),
        "largest_block": largest,
        "fragmentation_index": (round(1.0 - largest / n_free, 4)
                                if n_free else 0.0),
        # which per-host block sizes admit right now; best_block names
        # the concrete chips size-4 (the modal slice host) would take.
        "admissible_block_sizes": [s for s in HOST_BLOCK_SIZES
                                   if s <= largest],
        "ownership_known": bool(snapshot.get("ownership_known", True)),
    }
    probe = min(4, largest)
    if probe > 0:
        entry["best_block"] = placement.best_block(free, probe)
    return entry


def _inventory_key(raw: object) -> tuple:
    """Cheap change-detection key over a reported inventory section —
    building it costs a fraction of re-deriving host_capacity, so
    steady-state passes (the common case: a fleet that did not move
    between scrapes) skip the derivation entirely."""
    if not isinstance(raw, dict):
        return ("unknown",)
    held = raw.get("held") or {}
    return (raw.get("total"),
            tuple(raw.get("free") or ()),
            tuple(raw.get("warm") or ()),
            tuple(raw.get("fenced") or ()),
            tuple(sorted(held.items())),
            raw.get("warm_ready"),
            bool(raw.get("ownership_known", True)))


# --- the master plane ---


class CapacityPlane:
    """Fleet capacity rollup over the FleetCollector's node entries.

    Shares the collector's shard federation for free: a sharded
    replica's collector only scrapes the nodes it owns, so this
    plane's /capacity payload covers exactly the same slice /fleet
    does (the payload says which shards, like /fleet).
    """

    def __init__(self, fleet, cfg=None, elastic=None, shares=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.cfg = cfg
        self.fleet = fleet
        self.elastic = elastic
        #: optional vchip.shares.ShareRegistry — when wired, /capacity
        #: reports fractional free capacity (weight-unit headroom on
        #: shared chips) next to the whole-chip numbers.
        self.shares = shares
        self._lock = OrderedLock("capacity.trend")
        #: trailing (wall time, free chips, queue depth) samples the
        #: headroom forecast derives its trends from (one per observe()
        #: — i.e. one per collection pass).
        self._trend: deque[tuple[float, int, float]] = deque(
            maxlen=max(2, int(cfg.capacity_trend_samples)))
        #: node -> (inventory key, derived entry): a steady-state
        #: collection pass (and a polled /capacity read) re-derives
        #: only the nodes whose inventory actually changed — the 5%
        #: collect-overhead budget (bench_capacity.py) is met by not
        #: recomputing a fleet that did not move. Entries are
        #: read-only once built; concurrent derivers (collect pass vs
        #: a route thread) at worst waste a recompute, never corrupt.
        self._host_cache: dict[str, tuple[tuple, dict]] = {}

    def _derive_hosts(self, nodes: dict[str, dict]) -> dict[str, dict]:
        """Per-node derived capacity views, cache-deduped by inventory.
        A STALE node (the collector kept its last entry because the
        worker stopped answering) derives as capacity_unknown: its
        last-known chips must not count as live capacity — a feasibility
        verdict resting on a dead node's free chips would green-light
        mounts that are guaranteed to fail."""
        hosts: dict[str, dict] = {}
        fresh_cache: dict[str, tuple[tuple, dict]] = {}
        for node, entry in nodes.items():
            stale = bool(entry.get("stale"))
            raw = None if stale else entry.get("capacity")
            key = ("stale",) if stale else _inventory_key(raw)
            cached = self._host_cache.get(node)
            if cached is not None and cached[0] == key:
                derived = cached[1]
            else:
                derived = host_capacity(raw)
                if stale:
                    derived["stale"] = True
            fresh_cache[node] = (key, derived)
            hosts[node] = derived
        # replaced wholesale, keyed by node: evicted nodes leave with
        # their entries (same discipline as the collector's node map)
        self._host_cache = fresh_cache
        return hosts

    # --- per-pass observation (called by FleetCollector.collect_once) ---

    def observe(self, nodes: dict[str, dict]) -> dict:
        """Derive the fleet capacity view from one collection pass's
        node entries, update the fleet gauges, the slice-feasibility
        SLO counters and the trend window. Exception-safe by contract
        with the collector (a capacity bug must not fail telemetry)."""
        hosts = self._derive_hosts(nodes)
        fleet = self._fleet_rollup(hosts)
        feasibility = self._feasibility(hosts, fleet)
        tracked = [e for e in feasibility.values() if e["tracked"]]
        # The SLO's bad events are FRAGMENTATION-caused denials only
        # (admissible-after-defrag): a fully-utilized fleet legitimately
        # has no room for big slices and must not page — that's the
        # headroom forecast's story. Burn means defrag would unlock
        # blocked slice shapes.
        frag_blocked = sum(1 for e in tracked
                           if e["verdict"] == "admissible-after-defrag")
        if tracked:
            CAPACITY_SIZE_FEASIBLE.inc(float(len(tracked)
                                             - frag_blocked))
            CAPACITY_SIZE_INFEASIBLE.inc(float(frag_blocked))
        CAPACITY_FREE_CHIPS.set(float(fleet["free"]))
        CAPACITY_FRAG_INDEX.set(fleet["fragmentation_index"])
        queue_depth = self._queue_depth(nodes)
        with self._lock:
            self._trend.append((time.time(), fleet["free"], queue_depth))
        return {"hosts": hosts, "fleet": fleet,
                "feasibility": feasibility}

    @staticmethod
    def _fleet_rollup(hosts: dict[str, dict]) -> dict:
        total = free = held = warm = fenced = 0
        achievable = 0
        largest = 0
        reporting = 0
        for entry in hosts.values():
            if entry.get("capacity_unknown"):
                continue
            reporting += 1
            total += entry["total"]
            free += entry["free"]
            held += entry["held"]
            warm += entry["warm"]
            fenced += entry["fenced"]
            achievable += entry["largest_block"]
            largest = max(largest, entry["largest_block"])
        return {
            "hosts": len(hosts),
            "hosts_reporting": reporting,
            "total": total,
            "free": free,
            "held": held,
            "warm": warm,
            "fenced": fenced,
            "largest_block": largest,
            # Weighted fleet index: 1 - sum(largest per-host block) /
            # free — the fraction of free chips a contiguity-demanding
            # mount CANNOT reach without defragmentation.
            "fragmentation_index": (round(1.0 - achievable / free, 4)
                                    if free else 0.0),
        }

    def _feasibility(self, hosts: dict[str, dict],
                     fleet: dict) -> dict[str, dict]:
        """The per-size allocation-feasibility table: for every
        accelerator type the topology module knows, would an intent of
        that shape admit right now (enough hosts each holding an
        ICI-connected free block of chips_per_host), only after a
        defragmentation pass (enough hosts with the free+warm CHIPS but
        not the contiguity — warm holders are reclaimable bookings), or
        not at all. Blocking hosts are named so the defragmenter (and
        the operator) know where to aim."""
        from gpumounter_tpu.master import topology
        name_cap = max(1, int(self.cfg.capacity_blocking_hosts_max))
        # One host scan per DISTINCT chips-per-host size (4 values
        # cover every published shape), not per accelerator type (20+):
        # the whole-fleet observe() pass runs this every collection and
        # must stay inside the collect-overhead budget.
        sizes = {t.chips_per_host_count
                 for t in topology._TOPOLOGIES.values()}
        reporting = [(node, entry) for node, entry in sorted(hosts.items())
                     if not entry.get("capacity_unknown")]
        by_size: dict[int, tuple[list[str], list[str]]] = {}
        for cph in sizes:
            now: list[str] = []
            after: list[str] = []
            for node, entry in reporting:
                if entry["largest_block"] >= cph:
                    now.append(node)
                elif entry["free"] + entry["warm"] >= cph:
                    after.append(node)
            by_size[cph] = (now, after)
        table: dict[str, dict] = {}
        for accel_type, topo in sorted(topology._TOPOLOGIES.items()):
            cph = topo.chips_per_host_count
            needed = topo.num_hosts
            now, after = by_size[cph]
            if len(now) >= needed:
                verdict = "admissible"
                blocking: list[str] = []
            elif len(now) + len(after) >= needed:
                verdict = "admissible-after-defrag"
                blocking = after[:name_cap]
            else:
                verdict = "infeasible"
                blocking = after[:name_cap]
            table[accel_type] = {
                "verdict": verdict,
                "chips_per_host": cph,
                "hosts_needed": needed,
                "total_chips": topo.total_chips,
                "hosts_admissible_now": len(now),
                "hosts_after_defrag": len(now) + len(after),
                "blocking_hosts": blocking,
                # Sizes the fleet could never host don't feed the SLO:
                # they would burn budget forever on a small fleet.
                "tracked": topo.total_chips <= fleet["total"],
            }
        return table

    def _shares_view(self, hosts: dict[str, dict],
                     fleet: dict) -> dict | None:
        """Fractional free capacity in weight units: whole free chips
        contribute a full vchip_weight_capacity each, shared chips
        contribute their remaining headroom. A shared chip whose host
        is NOT currently reporting (stale node, scrape fallback — the
        same degradation the whole-chip inventory has) is counted as
        capacity_unknown, never as free headroom: its books may be
        arbitrarily stale, and advertising it would green-light shares
        onto a chip nobody can confirm exists (the PR 14 capacity-none
        contract, applied to fractions)."""
        if self.shares is None:
            return None
        capacity = int(self.cfg.vchip_weight_capacity)
        view = {"weight_capacity": capacity, "shares": 0, "chips": 0,
                "booked_weight": 0, "share_headroom": 0,
                "unknown_chips": 0}
        for _uuid, holders in self.shares.shared_chips().items():
            view["shares"] += len(holders)
            node = holders[0].node
            entry = hosts.get(node)
            if entry is None or entry.get("capacity_unknown"):
                view["unknown_chips"] += 1
                continue
            load = sum(s.weight for s in holders)
            view["chips"] += 1
            view["booked_weight"] += load
            view["share_headroom"] += max(0, capacity - load)
        view["capacity_unknown"] = view["unknown_chips"] > 0
        # Whole-chip free capacity expressed in the same unit, so the
        # admission question "does weight W x N chips fit?" reads off
        # one number. Unknown chips contribute NOTHING here.
        view["effective_free_weight"] = (fleet["free"] * capacity
                                         + view["share_headroom"])
        return view

    def blocked_hosts(self, max_age_s: float | None = None,
                      ) -> frozenset[str]:
        """Hosts named as blocking in the feasibility table — the
        defragmenter's work queue. Consumers (the vchip packer, the
        allocator's placement hint) treat these as last-resort
        placements: packing fresh work there undoes the defrag plan.
        Never raises; degrades to the empty set."""
        try:
            nodes = self.fleet.payload(max_age_s=max_age_s).get(
                "nodes", {})
            hosts = self._derive_hosts(nodes)
            fleet = self._fleet_rollup(hosts)
            out: set[str] = set()
            for entry in self._feasibility(hosts, fleet).values():
                if entry["verdict"] == "admissible-after-defrag":
                    out.update(entry["blocking_hosts"])
            return frozenset(out)
        except Exception as exc:  # noqa: BLE001 — the hint is advisory
            logger.warning("blocked-host derivation failed: %s", exc)
            return frozenset()

    @staticmethod
    def _queue_depth(nodes: dict[str, dict]) -> float:
        from gpumounter_tpu.obs.fleet import merge_tenants
        depth = 0.0
        for snap in merge_tenants(nodes).values():
            value = snap.get("queue_depth")
            if isinstance(value, (int, float)):
                depth += float(value)
        return depth

    # --- the /capacity payload ---

    def payload(self, max_age_s: float | None = None,
                accel_type: str | None = None) -> dict:
        """The GET /capacity response. Refreshes the underlying fleet
        rollup when stale (single-flight, exactly like /fleet), derives
        the capacity view from the same node entries, and joins the
        tenant demand signals into the headroom forecast. With
        `accel_type`, the feasibility table is filtered to that type
        (raises KeyError for an unknown one — the route maps it to
        404)."""
        rollup = self.fleet.payload(max_age_s=max_age_s)
        nodes = rollup["nodes"]
        hosts = self._derive_hosts(nodes)
        fleet = self._fleet_rollup(hosts)
        feasibility = self._feasibility(hosts, fleet)
        if accel_type is not None:
            norm = accel_type.strip().lower()
            feasibility = {norm: feasibility[norm]}
        payload = {
            "at": rollup.get("at"),
            "nodes": hosts,
            "fleet": fleet,
            "feasibility": feasibility,
            "headroom": self._headroom(nodes, fleet),
            "demand": self._demand(fleet),
        }
        shares_view = self._shares_view(hosts, fleet)
        if shares_view is not None:
            payload["shares"] = shares_view
        if "shard" in rollup:
            payload["shard"] = rollup["shard"]
        return payload

    def _headroom(self, nodes: dict[str, dict], fleet: dict) -> dict:
        """Free capacity joined against the tenant-plane demand
        signals: current queue depth and tokens/sec, plus the trends
        the trailing observe() window saw. The forecast is deliberately
        coarse — ok / tight / exhausted — because it feeds operators
        and the future autoscaler's guardrails, not a control loop."""
        from gpumounter_tpu.obs.fleet import merge_tenants
        merged = merge_tenants(nodes)
        queue_depth = 0.0
        tokens_per_s = 0.0
        for snap in merged.values():
            value = snap.get("queue_depth")
            if isinstance(value, (int, float)):
                queue_depth += float(value)
            tokens_per_s += float(snap.get("tokens_per_s", 0.0) or 0.0)
        with self._lock:
            trend = list(self._trend)
        trend_out = {"window_s": 0.0, "free_delta": 0, "queue_delta": 0.0}
        if len(trend) >= 2:
            (t0, free0, q0), (t1, free1, q1) = trend[0], trend[-1]
            trend_out = {"window_s": round(t1 - t0, 3),
                         "free_delta": free1 - free0,
                         "queue_delta": round(q1 - q0, 3)}
        free = fleet["free"]
        total = fleet["total"]
        tight_ratio = float(self.cfg.capacity_tight_free_ratio)
        if total and free == 0:
            forecast = "exhausted"
        elif total and (free / total < tight_ratio
                        or queue_depth > free):
            forecast = "tight"
        else:
            forecast = "ok"
        return {
            "free_chips": free,
            "warm_chips": fleet["warm"],
            "queue_depth": queue_depth,
            "tokens_per_s": round(tokens_per_s, 3),
            "tenants": len(merged),
            "trend": trend_out,
            "forecast": forecast,
        }

    def _demand(self, fleet: dict) -> dict:
        """Declared-intent demand vs free capacity: the scriptable
        "does what operators asked for still fit" verdict the CLI's
        exit code keys off."""
        intents = 0
        desired = 0
        actual = 0
        if self.elastic is not None:
            try:
                listed = self.elastic.store.list()
            except Exception as exc:  # noqa: BLE001 — demand is advisory;
                # any store failure (outage, staleness bound) degrades
                # to "no declared demand" rather than failing /capacity
                logger.warning("intent list for capacity demand "
                               "failed: %s", exc)
                listed = []
            for namespace, pod_name, intent in listed:
                intents += 1
                desired += int(intent.desired_chips)
                status = self.elastic.status_for(namespace, pod_name)
                if status and isinstance(status.get("actual"), int):
                    actual += status["actual"]
        gap = max(0, desired - actual)
        return {
            "intents": intents,
            "desired_chips": desired,
            "actual_chips": actual,
            "gap": gap,
            "satisfiable": gap <= fleet["free"] + fleet["warm"],
        }

    # --- rejected-for-capacity admissions ---

    def record_rejection(self, node: str, namespace: str, pod: str,
                         chips: int) -> dict:
        """Stamp the feasibility verdict for a rejected-for-capacity
        admission into the audit trail (the audit subscriber mirrors it
        onto the flight recorder's timeline). Uses the LAST collected
        rollup — no forced refresh; the verdict describes what the
        plane believed when the intent failed to place. Never raises."""
        verdict: dict = {"node": node, "want": int(chips)}
        try:
            nodes = self.fleet.payload(max_age_s=None).get("nodes", {})
            hosts = self._derive_hosts(nodes)
            entry = hosts.get(node) or {"capacity_unknown": True}
            fleet = self._fleet_rollup(hosts)
            if entry.get("capacity_unknown"):
                verdict["node_view"] = "unknown"
            else:
                verdict.update(
                    node_free=entry["free"],
                    node_largest_block=entry["largest_block"],
                    node_fragmentation_index=entry["fragmentation_index"])
                if entry["free"] >= int(chips) > entry["largest_block"]:
                    verdict["cause"] = "fragmentation"
                else:
                    verdict["cause"] = "exhaustion"
            verdict["fleet_free"] = fleet["free"]
            verdict["fleet_fragmentation_index"] = \
                fleet["fragmentation_index"]
        except Exception as exc:  # noqa: BLE001 — the verdict is
            # advisory; a capacity-plane bug must never mask the real
            # admission failure the caller is about to report
            logger.exception("capacity rejection verdict failed: %s", exc)
            verdict["error"] = f"{type(exc).__name__}: {exc}"
        outcome = (f"infeasible: want {chips} chip(s) on {node} "
                   f"(cause: {verdict.get('cause', 'unknown')}, node "
                   f"free {verdict.get('node_free', '?')}, largest "
                   f"block {verdict.get('node_largest_block', '?')}, "
                   f"fleet free {verdict.get('fleet_free', '?')})")
        AUDIT.record("capacity.reject", actor="capacity-plane",
                     namespace=namespace, pod=pod, outcome=outcome,
                     **verdict)
        return verdict

    # --- recovered capacity (the defragmenter's follow-through) ---

    def record_recovery(self, *, cause: str, plan_id: str,
                        fragmentation_before: float,
                        fragmentation_after: float,
                        moves: int) -> dict:
        """Close the loop `capacity.reject` opened: a completed defrag
        run re-collects capacity and stamps what it bought back into
        the audit trail (the audit subscriber mirrors it onto the
        flight recorder's timeline, so an incident review sees the
        recovery next to the rejections it answers). Uses the LAST
        collected rollup — the defrag controller forces the re-collect
        before calling. Never raises."""
        record: dict = {
            "cause": cause, "plan_id": plan_id, "moves": int(moves),
            "fragmentation_before": round(float(fragmentation_before), 4),
            "fragmentation_after": round(float(fragmentation_after), 4),
        }
        try:
            hosts = self._derive_hosts(
                self.fleet.payload(max_age_s=None).get("nodes", {}))
            fleet = self._fleet_rollup(hosts)
            record["fleet_free"] = fleet["free"]
            record["fleet_largest_block"] = fleet["largest_block"]
        except Exception as exc:  # noqa: BLE001 — the stamp is
            # advisory; a capacity-plane bug must never turn a finished
            # defrag run into a failure after the moves landed
            logger.exception("capacity recovery stamp failed: %s", exc)
            record["error"] = f"{type(exc).__name__}: {exc}"
        outcome = (f"recovered: fleet fragmentation "
                   f"{record['fragmentation_before']} -> "
                   f"{record['fragmentation_after']} after {moves} "
                   f"move(s) (cause: {cause})")
        AUDIT.record("capacity.recovered", actor="capacity-plane",
                     outcome=outcome, **record)
        return record


# --- process-global plane (the reconciler's hook) ---

_PLANE: CapacityPlane | None = None


def register_plane(plane: CapacityPlane) -> None:
    """MasterApp construction registers its plane here so subsystems
    without a direct reference (the elastic reconciler's
    capacity-limited branch) can stamp rejection verdicts. Latest
    wins — one live MasterApp per process is the deployed shape; test
    stacks that build several get the newest, which is what their
    requests hit anyway."""
    global _PLANE
    _PLANE = plane


def record_rejection(node: str, namespace: str, pod: str,
                     chips: int) -> None:
    """Module-level rejection stamp: no-op when no plane is registered
    (a bare worker process, unit tests), never raises."""
    plane = _PLANE
    if plane is not None:
        plane.record_rejection(node, namespace, pod, chips)


def blocked_hosts() -> frozenset[str]:
    """Module-level blocked-host hint: empty when no plane is
    registered (a bare worker process, unit tests), never raises."""
    plane = _PLANE
    if plane is None:
        return frozenset()
    return plane.blocked_hosts(max_age_s=None)
