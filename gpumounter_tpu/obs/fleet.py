"""Fleet telemetry plane: master-side federation of worker metrics.

After PR 4 each daemon answers only for itself; this module gives the
master one pane over every node. A FleetCollector periodically pulls
each worker's telemetry snapshot — mount-latency histogram (with trace
exemplars), mount/unmount counters, warm-pool hit rate, per-tenant
device-access counts, eBPF program-swap count — over the existing
pooled channels via the CollectTelemetry RPC, degrades to scraping the
worker's HTTP /metrics exposition for legacy workers (UNIMPLEMENTED or
an unparseable payload), and rolls everything into a node-keyed fleet
model served at /fleet and fed to the SLO burn-rate engine (obs/slo.py).

No double counting by construction: per-node state is a dict keyed by
node name whose entries are replaced wholesale each pass, and every
worker-reported number is an absolute counter/histogram value, never a
delta — so a restarted collector (or an extra collection pass) cannot
inflate the rollup. The chaos harness asserts exactly that (invariant 8).

Stdlib-only (lazy-grpc policy: the worker imports the snapshot half on
its mount path; RPC transport is injected via the client factory).
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request

from gpumounter_tpu.cgroup.ebpf import DEVICE_TELEMETRY
from gpumounter_tpu.obs import trace
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    estimate_quantile,
)

logger = get_logger("obs.fleet")

TELEMETRY_SCHEMA = "tpumounter-telemetry/1"

FLEET_COLLECTIONS = REGISTRY.counter(
    "tpumounter_fleet_collections_total",
    "Fleet telemetry collection passes by node outcome (rpc / scrape / "
    "error)")
FLEET_NODES = REGISTRY.gauge(
    "tpumounter_fleet_nodes",
    "Nodes in the last fleet rollup")
FLEET_COLLECT_DURATION = REGISTRY.histogram(
    "tpumounter_fleet_collect_duration_seconds",
    "Wall time of one whole-fleet collection pass")

#: (exposition name, snapshot counter key) — the counters a worker
#: snapshot carries and the scrape fallback recovers. Reading by name
#: through REGISTRY.find keeps this module import-light (it must not
#: drag worker-only modules into the master).
_COUNTER_NAMES = (
    ("tpumounter_mount_total", "mount_total"),
    ("tpumounter_unmount_total", "unmount_total"),
    ("tpumounter_warm_pool_hits_total", "warm_pool_hits"),
    ("tpumounter_warm_pool_misses_total", "warm_pool_misses"),
    ("tpumounter_mount_rollback_failures_total", "rollback_failures"),
    ("tpumounter_ebpf_program_swaps_total", "ebpf_program_swaps"),
)

#: master-side counters folded into the rollup (heal / migration story
#: lives in the master process, not on workers).
_MASTER_COUNTER_NAMES = (
    ("tpumounter_chips_healed_total", "heals"),
    ("tpumounter_chips_heal_failures_total", "heal_failures"),
    ("tpumounter_migrations_total", "migrations"),
    ("tpumounter_worker_breaker_trips_total", "breaker_trips"),
    # Capacity plane (obs/capacity.py): per-pass accelerator-size
    # feasibility evaluations — the slice-feasibility SLO's ratio.
    ("tpumounter_capacity_size_feasible_total", "slice_feasible"),
    ("tpumounter_capacity_size_infeasible_total", "slice_infeasible"),
)


def _labeled_totals(metric) -> dict[str, float]:
    """Counter snapshot folded to {single-label-value or "": total}."""
    if metric is None or not isinstance(metric, (Counter, Gauge)):
        return {}
    out: dict[str, float] = {}
    for key, value in metric.snapshot().items():
        label = key[0][1] if key else ""
        out[label] = out.get(label, 0.0) + value
    return out


def worker_telemetry_snapshot(cfg=None, registry=None) -> dict:
    """This process's telemetry snapshot — the CollectTelemetry payload
    and the worker ops port's /telemetry body. All values are absolute
    (counters since process start), so consumers can diff or re-read
    freely without double counting."""
    reg = registry or REGISTRY
    latency = reg.find("tpumounter_mount_latency_seconds")
    mount_hist: dict = {"buckets": [], "count": 0, "sum": 0.0,
                       "exemplars": []}
    if isinstance(latency, Histogram):
        counts = [0] * (len(latency.buckets) + 1)
        total = 0.0
        exemplars = []
        for entry in latency.snapshot().values():
            for i, c in enumerate(entry["counts"]):
                counts[i] += c
            total += entry["sum"]
            for idx, (tid, value, ts) in entry["exemplars"].items():
                bound = (latency.buckets[idx]
                         if idx < len(latency.buckets) else "+Inf")
                exemplars.append({"le": bound, "trace_id": tid,
                                  "value": value, "at": ts})
        mount_hist = {
            "buckets": [[b, counts[i]] for i, b in enumerate(latency.buckets)],
            "count": counts[-1],
            "sum": round(total, 6),
            "exemplars": exemplars,
        }
    counters: dict[str, dict[str, float]] = {}
    for name, key in _COUNTER_NAMES:
        counters[key] = _labeled_totals(reg.find(name))
    device_access: dict[str, dict[str, float]] = {}
    for (tenant, kind), value in DEVICE_TELEMETRY.counts().items():
        device_access.setdefault(tenant, {})[kind] = value
    from gpumounter_tpu.obs.tenants import TENANTS
    # Span export (the fleet trace plane, obs/assembly.py): the newest
    # span_export_max finished spans from this process's ring ride the
    # snapshot; the master dedupes by span id, so a cumulative ring
    # re-sent every pass costs nothing but the wire bytes the cap
    # bounds — and 0 really disables the export (an operator's
    # bandwidth valve), it does not fall back to the default.
    # Legacy consumers ignore the extra key.
    span_cap = int(getattr(cfg, "span_export_max", 512)) \
        if cfg is not None else 512
    snap = {
        "schema": TELEMETRY_SCHEMA,
        "at": round(time.time(), 3),
        "mount_latency": mount_hist,
        "counters": counters,
        "device_access": device_access,
        # Tenant-side snapshots the jaxside SDK published to this
        # worker's ops port (obs/tenants.py): cumulative, capped at
        # 256 + _overflow. Legacy consumers ignore the extra key.
        "tenants": TENANTS.export(),
        "spans": trace.TRACER.ring.tail(span_cap),
    }
    if cfg is not None and getattr(cfg, "node_name", ""):
        snap["node"] = cfg.node_name
    return snap


def parse_telemetry(raw: object) -> dict | None:
    """Tolerant payload parse: absent (empty/None), wrong-typed,
    non-JSON, non-object, or wrong-schema input — anything a legacy or
    buggy peer could put on the wire — yields None, never an exception.
    The collector then falls back to the HTTP scrape path."""
    if not raw or not isinstance(raw, str):
        return None
    try:
        doc = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(doc, dict) or doc.get("schema") != TELEMETRY_SCHEMA:
        return None
    return doc


# --- HTTP-scrape fallback (legacy workers) ---

_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^{}]*)\})?\s+(?P<value>[-+0-9.eE]+|[+-]?Inf|NaN)")
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_prometheus_text(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Prometheus text exposition -> {metric name: [(labels, value)]}.
    Unparseable lines are skipped (a legacy worker's exposition is not
    under our control)."""
    out: dict[str, list[tuple[dict, float]]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        match = _SERIES_RE.match(line)
        if match is None:
            continue
        labels = dict(_LABEL_RE.findall(match.group("labels") or ""))
        try:
            value = float(match.group("value").replace("Inf", "inf"))
        except ValueError:
            continue
        out.setdefault(match.group("name"), []).append((labels, value))
    return out


def snapshot_from_prometheus(text: str) -> dict:
    """Build the same snapshot shape worker_telemetry_snapshot produces
    from a scraped /metrics exposition — the degraded path for workers
    without the telemetry RPC (no exemplars there; the classic text
    format cannot carry them)."""
    series = parse_prometheus_text(text)
    buckets: dict[float, float] = {}
    inf_count = 0.0
    for labels, value in series.get("tpumounter_mount_latency_seconds_bucket",
                                    []):
        le = labels.get("le", "")
        if le == "+Inf":
            inf_count += value
        else:
            try:
                bound = float(le)
            except ValueError:
                continue
            buckets[bound] = buckets.get(bound, 0.0) + value
    total = sum(v for _, v in
                series.get("tpumounter_mount_latency_seconds_sum", []))
    counters: dict[str, dict[str, float]] = {}
    for name, key in _COUNTER_NAMES:
        folded: dict[str, float] = {}
        for labels, value in series.get(name, []):
            label = next(iter(sorted(labels.values())), "")
            folded[label] = folded.get(label, 0.0) + value
        counters[key] = folded
    device_access: dict[str, dict[str, float]] = {}
    for labels, value in series.get("tpumounter_device_access_total", []):
        tenant = labels.get("tenant", "")
        if tenant:
            device_access.setdefault(tenant, {})[
                labels.get("kind", "")] = value
    return {
        "schema": TELEMETRY_SCHEMA,
        "at": round(time.time(), 3),
        "mount_latency": {
            "buckets": [[b, buckets[b]] for b in sorted(buckets)],
            "count": inf_count,
            "sum": total,
            "exemplars": [],
        },
        "counters": counters,
        "device_access": device_access,
        "tenants": {},  # the classic exposition cannot carry them
        "spans": [],    # ditto — the scrape fallback degrades to none
        # Chip indices never become labels, so the classic exposition
        # cannot carry the inventory either: a legacy worker's node
        # reports no capacity section (obs/capacity.py marks it
        # capacity_unknown instead of pretending it is empty).
        "capacity": None,
    }


# --- rollup helpers ---

def _hist_quantile_ms(hist: dict, q: float) -> float:
    pairs = hist.get("buckets") or []
    count = hist.get("count", 0)
    if not pairs or not count:
        return 0.0
    bounds = tuple(b for b, _ in pairs)
    counts = [c for _, c in pairs] + [count]
    return round(estimate_quantile(bounds, counts, q) * 1000.0, 3)


def _counter(snapshot: dict, key: str, label: str | None = None) -> float:
    folded = (snapshot.get("counters") or {}).get(key) or {}
    if label is None:
        return float(sum(folded.values()))
    return float(folded.get(label, 0.0))


def _node_rollup(snapshot: dict) -> dict:
    hist = snapshot.get("mount_latency") or {}
    hits = _counter(snapshot, "warm_pool_hits")
    misses = _counter(snapshot, "warm_pool_misses")
    lookups = hits + misses
    return {
        "mount": {
            "count": hist.get("count", 0),
            "p50_ms": _hist_quantile_ms(hist, 0.50),
            "p95_ms": _hist_quantile_ms(hist, 0.95),
            "success": _counter(snapshot, "mount_total", "success"),
            "error": _counter(snapshot, "mount_total", "error"),
            # raw cumulative bucket pairs so the fleet view can merge
            # histograms across nodes (same bucket layout everywhere)
            "buckets": list(hist.get("buckets") or []),
        },
        "warm_pool": {
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / lookups, 4) if lookups else 0.0,
        },
        "rollback_failures": _counter(snapshot, "rollback_failures"),
        "ebpf_program_swaps": _counter(snapshot, "ebpf_program_swaps"),
        "device_access": snapshot.get("device_access") or {},
        "tenants": snapshot.get("tenants") or {},
        # The per-host chip inventory (obs/capacity.py) rides the node
        # entry verbatim: the capacity plane derives fragmentation and
        # feasibility from it fleet-side, and None (legacy worker /
        # scrape fallback) stays None so consumers can tell "empty"
        # from "unknown".
        "capacity": snapshot.get("capacity"),
        "exemplars": (snapshot.get("mount_latency") or {}).get(
            "exemplars", []),
    }


# --- tenant merge (the jaxside SDK series, fleet-wide) ---

def merge_tenants(nodes: dict[str, dict]) -> dict[str, dict]:
    """tenant -> latest snapshot across every node entry, stamped with
    the node it came from. Keyed by tenant name so a tenant seen on two
    nodes (mid-migration republish, shared in-process test stacks) is
    counted ONCE — the freshest `at` wins; snapshots are cumulative, so
    taking the latest never loses events."""
    merged: dict[str, dict] = {}
    for node, entry in sorted(nodes.items()):
        for tenant, snap in (entry.get("tenants") or {}).items():
            if not isinstance(snap, dict):
                continue
            best = merged.get(tenant)
            if best is None or snap.get("at", 0) >= best.get("at", 0):
                merged[tenant] = {**snap, "node": node}
    return merged


def tenants_fleet_rollup(merged: dict[str, dict]) -> dict:
    """Fleet-wide tenant aggregates — the SLO engine's tenant inputs
    (obs/slo.py): cumulative disruption-free/disrupted minutes, and a
    per-cause merged downtime histogram for the p95 tenant-visible
    downtime objectives."""
    clean = disrupted = 0.0
    windows_total = 0.0
    seconds_total = 0.0
    open_windows = 0
    steps = 0.0
    downtime: dict[str, dict] = {}
    for snap in merged.values():
        minutes = snap.get("minutes") or {}
        total = float(minutes.get("total", 0))
        bad = float(minutes.get("disrupted", 0))
        clean += max(0.0, total - bad)
        disrupted += bad
        steps += float((snap.get("steps") or {}).get("count", 0))
        dis = snap.get("disruption") or {}
        windows_total += float(dis.get("total_windows", 0))
        seconds_total += float(dis.get("total_seconds", 0.0))
        open_windows += len(dis.get("open") or [])
        for cause, entry in (dis.get("by_cause") or {}).items():
            agg = downtime.setdefault(cause, {"buckets": {}, "count": 0.0,
                                              "seconds": 0.0})
            agg["count"] += float(entry.get("windows", 0))
            agg["seconds"] += float(entry.get("seconds", 0.0))
            for bound, cum in entry.get("buckets") or []:
                agg["buckets"][float(bound)] = \
                    agg["buckets"].get(float(bound), 0.0) + float(cum)
    return {
        "tenants": len(merged),
        "steps": steps,
        "tenant_clean_minutes": clean,
        "tenant_disrupted_minutes": disrupted,
        "disruption_windows": windows_total,
        "disruption_seconds": round(seconds_total, 4),
        "open_windows": open_windows,
        "downtime": {
            cause: {
                "buckets": [[b, agg["buckets"][b]]
                            for b in sorted(agg["buckets"])],
                "count": agg["count"],
                "seconds": round(agg["seconds"], 4),
            } for cause, agg in sorted(downtime.items())},
    }


class FleetCollector:
    """Periodic master-side federation of every worker's telemetry.

    `workers` is the WorkerRegistry (node -> address + the shared
    circuit breaker); `client_factory` builds WorkerClients over the
    pooled channels. Collection per node: CollectTelemetry RPC first;
    UNIMPLEMENTED (legacy worker) or an unparseable payload degrades to
    scraping http://<ip>:<metrics_port>/metrics. A node that answers
    neither keeps its previous entry, marked stale with the error — a
    blip must not blank a node out of the fleet view.
    """

    def __init__(self, workers, client_factory, cfg=None, slo=None,
                 shards=None, span_store=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.cfg = cfg
        self.workers = workers
        self.client_factory = client_factory
        self.slo = slo
        #: remote-span store (obs/assembly.py): every collected
        #: snapshot's `spans` section federates here, deduplicated by
        #: span id, so GET /trace/<id> can join master + worker halves.
        if span_store is None:
            from gpumounter_tpu.obs.assembly import REMOTE_SPANS
            span_store = REMOTE_SPANS
        self.span_store = span_store
        #: optional ShardManager (master/shard.py): an active sharded
        #: replica collects only the nodes it owns — N replicas split
        #: the scrape fan-out instead of each polling the whole fleet —
        #: and the payload says which slice this rollup covers.
        self.shards = shards
        #: optional CapacityPlane (obs/capacity.py): observes every
        #: collection pass (fragmentation gauges + the
        #: slice-feasibility SLO counters) and derives the /capacity
        #: payload from the same node entries /fleet serves — so the
        #: two panes can never disagree about what was collected.
        self.capacity = None
        #: optional HealthPlane (health/plane.py): scores every
        #: collection pass for gray failures (per-node p95 vs fleet
        #: median, error ratios, breaker + canary evidence) and drives
        #: the quarantine state machine. Same observer contract as the
        #: capacity plane: exception-isolated, fail-open.
        self.health = None
        #: optional ThroughputModel (autoscale/model.py): folds every
        #: pass's tenant snapshots into per-tenant batch->rate history.
        #: Same observer contract: exception-isolated, fail-open.
        self.autoscale_model = None
        self.interval_s = cfg.fleet_scrape_interval_s
        self._lock = OrderedLock("fleet.nodes")
        # Single-flight guard: concurrent stale observers (dashboards
        # polling /fleet at the interval edge) must not each launch
        # their own whole-fleet fan-out. RLock: collect_once holds it,
        # and refresh_if_stale re-enters it around the re-check.
        self._collect_mu = threading.RLock()
        #: node name -> node entry; replaced per pass, keyed by node, so
        #: collector restarts and repeated passes cannot double-count.
        self._nodes: dict[str, dict] = {}
        self._collected_at = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- collection ---

    def _scrape_url(self, ip: str) -> str:
        return f"http://{ip}:{self.cfg.metrics_port}/metrics"

    def _scrape_token(self) -> str | None:
        from gpumounter_tpu.utils.auth import resolve_read_token, resolve_token
        try:
            return resolve_read_token(self.cfg) or resolve_token(self.cfg)
        except Exception:  # noqa: BLE001 — scrape just goes credential-less
            return None

    def _scrape(self, ip: str) -> dict:
        req = urllib.request.Request(self._scrape_url(ip))
        token = self._scrape_token()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        with urllib.request.urlopen(req, timeout=self.cfg.rpc_telemetry_timeout_s) as resp:
            return snapshot_from_prometheus(resp.read().decode())

    @staticmethod
    def _is_unimplemented(exc: Exception) -> bool:
        code = getattr(exc, "code", None)
        if callable(code):
            try:
                return getattr(code(), "name", "") == "UNIMPLEMENTED"
            except Exception:  # noqa: BLE001 — non-grpc .code()
                return False
        return False

    def _collect_node(self, node: str, address: str) -> dict:
        ip = address.rsplit(":", 1)[0]
        entry = {"address": address, "collected_at": round(time.time(), 3)}
        snapshot = None
        mode = "rpc"
        quarantined = (self.health is not None
                       and node in self.health.excluded_hosts())
        try:
            with self.client_factory(address) as client:
                # kwarg only when set: absent means not-quarantined on
                # the wire, and plain stubs/legacy clients keep working.
                resp = (client.collect_telemetry(quarantined=True)
                        if quarantined else client.collect_telemetry())
            snapshot = parse_telemetry(resp.telemetry)
            if snapshot is None:
                logger.warning(
                    "worker %s answered CollectTelemetry with an "
                    "absent/unparseable payload; falling back to scrape",
                    node)
                mode = "scrape"
        except Exception as exc:  # noqa: BLE001 — gRPC boundary
            if not self._is_unimplemented(exc):
                raise
            mode = "scrape"  # legacy (reference) worker: no telemetry RPC
        if snapshot is None:
            snapshot = self._scrape(ip)
        entry["mode"] = mode
        # Federate the worker's span ring into the remote store (the
        # node entry itself stays span-free: /fleet is a rollup pane,
        # /trace/<id> is where the joined spans serve).
        self.span_store.ingest(node, snapshot.get("spans") or [])
        entry.update(_node_rollup(snapshot))
        return entry

    def _collect_one(self, node: str, ip: str) -> tuple[str, dict]:
        """One node's collection, exception-safe (runs on the fan-out
        pool). Collection spans are scrape noise: deferred-and-dropped
        so steady-state passes never rotate real operation traces out
        of the ring (per-thread contextvar, so this applies to the
        pool thread regardless of who triggered the pass)."""
        address = f"{ip}:{self.cfg.worker_port}"
        try:
            with trace.deferred():
                entry = self._collect_node(node, address)
            FLEET_COLLECTIONS.inc(outcome=entry["mode"])
        except Exception as exc:  # noqa: BLE001 — one node must not
            FLEET_COLLECTIONS.inc(outcome="error")  # fail the pass
            logger.warning("telemetry collection for %s failed: %s",
                           node, exc)
            with self._lock:
                prior = self._nodes.get(node)
            entry = dict(prior) if prior else {"address": address}
            entry["stale"] = True
            entry["error"] = f"{type(exc).__name__}: {exc}"
        retry_after = None
        breaker = getattr(self.workers, "breaker", None)
        if breaker is not None:
            retry_after = breaker.retry_after(address)
        entry["breaker"] = "open" if retry_after is not None else "closed"
        return node, entry

    def collect_once(self) -> dict:
        """One whole-fleet pass; returns the fresh rollup. Nodes come
        from the registry snapshot (the watch-maintained cache), so the
        pass costs zero Kubernetes API calls; per-node collection fans
        out across a bounded pool so a few deadline-burning workers
        cannot stall the pass serially. Single-flight under
        _collect_mu."""
        with self._collect_mu:
            t0 = time.monotonic()
            items = sorted(self.workers.registry_snapshot().items())
            if self.shards is not None and self.shards.active():
                items = [(node, ip) for node, ip in items
                         if self.shards.owns_node(node)]
            fresh: dict[str, dict] = {}
            if items:
                # Shared fan-out core (utils/fanout.py) instead of a
                # private per-pass pool: per-shard budgets keep one
                # slow rack from camping every core slot, and the pass
                # parallelism scales with the host instead of a fixed
                # 16. _collect_one is exception-safe, so a pass never
                # raises out of the core.
                from gpumounter_tpu.utils.fanout import get_core
                core = get_core(self.cfg)
                shard_of = None
                if self.shards is not None and self.shards.active() \
                        and hasattr(self.shards, "owner_shard"):
                    shard_of = lambda it: self.shards.owner_shard(it[0])  # noqa: E731
                for node, entry in core.run(
                        items, lambda it: self._collect_one(*it),
                        kind="fleet-collect", shard_of=shard_of):
                    fresh[node] = entry
            with self._lock:
                self._nodes = fresh
                self._collected_at = time.time()
            if self.capacity is not None:
                # Before payload(): the SLO counters this bumps ride
                # the rollup's master section, and the rollup ingested
                # below must describe THIS pass, not the previous one.
                try:
                    self.capacity.observe(fresh)
                except Exception:  # noqa: BLE001 — capacity is an
                    # observer; its bugs must not fail telemetry
                    logger.exception("capacity observation failed")
            if self.health is not None:
                # After capacity, before the rollup: the gray-failure
                # scorer reads the same per-pass node entries, so the
                # /health/nodes pane can never disagree with /fleet
                # about what was collected.
                try:
                    self.health.observe(fresh)
                except Exception:  # noqa: BLE001 — same observer
                    # contract as capacity: never fail telemetry
                    logger.exception("health observation failed")
            if self.autoscale_model is not None:
                # The throughput model learns from the same per-pass
                # tenant snapshots /tenants serves — the autoscaler can
                # never act on telemetry the panes don't show.
                try:
                    self.autoscale_model.observe_nodes(fresh)
                except Exception:  # noqa: BLE001 — same observer
                    # contract as capacity: never fail telemetry
                    logger.exception("throughput observation failed")
            FLEET_NODES.set(float(len(fresh)))
            FLEET_COLLECT_DURATION.observe(time.monotonic() - t0)
            rollup = self.payload(max_age_s=None)
            if self.slo is not None:
                self.slo.ingest(rollup)
                self.slo.evaluate()
            return rollup

    def refresh_if_stale(self, max_age_s: float | None) -> None:
        """Collect only when the cached rollup is older than max_age_s.
        Single-flight: a caller that lost the race re-checks under the
        collection lock and returns the winner's fresh rollup instead
        of launching a second fan-out (the FAQ's 'polling faster than
        the interval gets the cache' promise)."""
        if max_age_s is None:
            return

        def _stale() -> bool:
            with self._lock:
                return (time.time() - self._collected_at) > max_age_s

        if not _stale():
            return
        with self._collect_mu:
            if _stale():
                self.collect_once()

    # --- the fleet model ---

    def payload(self, max_age_s: float | None = None) -> dict:
        """The /fleet response. With `max_age_s`, a stale (or empty)
        rollup triggers a synchronous (single-flight) collection first —
        so the route works without the background loop (tests, CLI,
        bench)."""
        self.refresh_if_stale(max_age_s)
        with self._lock:
            nodes = {n: dict(e) for n, e in self._nodes.items()}
            at = self._collected_at
        now = time.time()
        for entry in nodes.values():
            if entry.get("stale"):
                # Age since the last SUCCESSFUL collect (collected_at is
                # only stamped on success — a stale entry keeps the old
                # one), so `tpumounter fleet` can tell a 20-second blip
                # from a node dark for an hour. A node that NEVER
                # answered has no collected_at: age is null, not ~0 —
                # "collected moments ago" would invert exactly the
                # distinction this field exists to make.
                entry["stale_age_s"] = (
                    round(max(0.0, now - entry["collected_at"]), 1)
                    if "collected_at" in entry else None)
        fleet = {
            "nodes": len(nodes),
            "mount_count": 0,
            "mount_success": 0.0,
            "mount_error": 0.0,
            "warm_pool_hits": 0.0,
            "warm_pool_misses": 0.0,
            "breakers_open": 0,
            "rollback_failures": 0.0,
        }
        worst_p95 = 0.0
        for entry in nodes.values():
            mount = entry.get("mount") or {}
            fleet["mount_count"] += mount.get("count", 0)
            fleet["mount_success"] += mount.get("success", 0.0)
            fleet["mount_error"] += mount.get("error", 0.0)
            warm = entry.get("warm_pool") or {}
            fleet["warm_pool_hits"] += warm.get("hits", 0.0)
            fleet["warm_pool_misses"] += warm.get("misses", 0.0)
            fleet["rollback_failures"] += entry.get("rollback_failures", 0.0)
            if entry.get("breaker") == "open":
                fleet["breakers_open"] += 1
            worst_p95 = max(worst_p95, mount.get("p95_ms", 0.0))
        lookups = fleet["warm_pool_hits"] + fleet["warm_pool_misses"]
        fleet["warm_pool_hit_rate"] = (
            round(fleet["warm_pool_hits"] / lookups, 4) if lookups else 0.0)
        fleet["worst_node_p95_ms"] = worst_p95
        # Fleet-wide latency quantiles from the merged histograms: sum
        # per-bound cumulative counts across nodes (same bucket layout
        # everywhere — one Histogram class).
        merged: dict[float, float] = {}
        merged_count = 0.0
        for entry in nodes.values():
            mount = entry.get("mount") or {}
            for bound, cum in mount.get("buckets") or []:
                merged[float(bound)] = merged.get(float(bound), 0.0) + cum
            merged_count += mount.get("count", 0)
        merged_hist = {"buckets": [[b, merged[b]] for b in sorted(merged)],
                       "count": merged_count}
        fleet["p50_ms"] = _hist_quantile_ms(merged_hist, 0.50)
        fleet["p95_ms"] = _hist_quantile_ms(merged_hist, 0.95)
        fleet["mount_buckets"] = merged_hist["buckets"]
        master = {key: (REGISTRY.find(name).total()
                        if isinstance(REGISTRY.find(name), Counter) else 0.0)
                  for name, key in _MASTER_COUNTER_NAMES}
        payload = {
            "at": round(at, 3),
            "interval_s": self.interval_s,
            "nodes": nodes,
            "fleet": fleet,
            "master": master,
            # Tenant-perceived series, merged fleet-wide (deduped by
            # tenant) — the SLO engine's tenant objectives read this.
            "tenants_fleet": tenants_fleet_rollup(merge_tenants(nodes)),
        }
        if self.shards is not None and self.shards.active():
            payload["shard"] = {
                "replica": self.shards.replica_id,
                "shardCount": self.shards.shard_count,
                "ownedShards": sorted(self.shards.owned_shards()),
            }
        return payload

    def tenants_payload(self, max_age_s: float | None = None) -> dict:
        """The /tenants response: the per-tenant disruption ledger,
        joined against the trace plane — every window with a trace id
        links to /trace/<id> and says whether that trace still resolves
        in THIS master's ring (migration/heal/evacuation spans are
        master-minted, so the join usually lands)."""
        self.refresh_if_stale(max_age_s)
        with self._lock:
            nodes = {n: dict(e) for n, e in self._nodes.items()}
            at = self._collected_at
        merged = merge_tenants(nodes)
        tenants: dict[str, dict] = {}
        for tenant, snap in sorted(merged.items()):
            dis = snap.get("disruption") or {}
            windows = []
            for w in dis.get("windows") or []:
                entry = dict(w)
                tid = entry.get("trace_id") or ""
                if tid:
                    entry["trace"] = f"/trace/{tid}"
                    entry["trace_resolves"] = \
                        trace.trace_payload(tid) is not None
                windows.append(entry)
            by_cause = {}
            for cause, agg in (dis.get("by_cause") or {}).items():
                buckets = [[float(b), float(c)]
                           for b, c in agg.get("buckets") or []]
                hist = {"buckets": buckets,
                        "count": float(agg.get("windows", 0))}
                by_cause[cause] = {
                    "windows": agg.get("windows", 0),
                    "seconds": agg.get("seconds", 0.0),
                    "p50_ms": _hist_quantile_ms(hist, 0.50),
                    "p95_ms": _hist_quantile_ms(hist, 0.95),
                }
            tenants[tenant] = {
                "node": snap.get("node", ""),
                "namespace": snap.get("namespace", ""),
                "pod": snap.get("pod", ""),
                "at": snap.get("at"),
                "steps": (snap.get("steps") or {}).get("count", 0),
                "tokens_per_s": snap.get("tokens_per_s", 0.0),
                "queue_depth": snap.get("queue_depth"),
                "minutes": snap.get("minutes") or {},
                "disruption": {
                    "open": dis.get("open") or [],
                    "windows": windows,
                    "by_cause": by_cause,
                    "total_windows": dis.get("total_windows", 0),
                    "total_seconds": dis.get("total_seconds", 0.0),
                },
            }
        return {
            "at": round(at, 3),
            "tenants": tenants,
            "fleet": tenants_fleet_rollup(merged),
        }

    # --- the poll loop (master/main.py) ---

    def start(self) -> "FleetCollector":
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="fleet-collector", daemon=True)
                self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                # Collection passes are background maintenance: deferred
                # spans keep steady-state scraping from rotating real
                # operation traces out of the ring (same discipline as
                # the elastic resync).
                with trace.deferred():
                    self.collect_once()
            except Exception as exc:  # noqa: BLE001 — keep the loop up
                logger.warning("fleet collection pass failed: %s", exc)
            self._stop.wait(self.interval_s)
