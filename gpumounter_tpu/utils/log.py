"""Structured logging for master/worker daemons.

Reference parity: pkg/util/log/log.go:9-30 (zap SugaredLogger, console encoder,
ISO8601 timestamps, Debug level, dual sink stdout + /var/log/GPUMounter/<file>.log).
Here: stdlib logging with an ISO8601 console formatter and optional file sink.

Two output modes (TPUMOUNTER_LOG_FORMAT, or init_logger(json_mode=...)):
  console  the zap-style tab-separated line (default)
  json     one JSON object per line: ts/level/logger/msg — and, whenever
           an obs.trace span is active, the trace id, so log lines and
           spans correlate (`tpumounter trace <id>` + grep trace_id).
The trace id is stamped by a logging.Filter in BOTH modes (console
formatting just doesn't render it); obs.trace is imported lazily inside
the filter because obs.trace itself logs through this module.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading

_LOCK = threading.Lock()
_INITIALIZED = False

_FMT = "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s"
_DATEFMT = "%Y-%m-%dT%H:%M:%S%z"


class _TraceIdFilter(logging.Filter):
    """Stamp the ambient trace id (obs.trace contextvar) on every record."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from gpumounter_tpu.obs.trace import current_trace_id
            record.trace_id = current_trace_id()
        except Exception:  # noqa: BLE001 — logging must never raise
            record.trace_id = ""
        return True


class JsonFormatter(logging.Formatter):
    """One JSON object per line; trace_id present only when traced."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record, _DATEFMT),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        trace_id = getattr(record, "trace_id", "")
        if trace_id:
            out["trace_id"] = trace_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def _make_formatter(json_mode: bool) -> logging.Formatter:
    if json_mode:
        return JsonFormatter(datefmt=_DATEFMT)
    return logging.Formatter(_FMT, datefmt=_DATEFMT)


def init_logger(log_dir: str | None = None, filename: str | None = None,
                level: int = logging.DEBUG,
                json_mode: bool | None = None) -> logging.Logger:
    """Initialise root logging: stdout always; file sink if log_dir given.

    Mirrors InitLogger(log.go:9-17): distinct filenames per daemon
    ("tpumounter-master.log" / "tpumounter-worker.log"), multi-sink.
    Safe to call more than once; later calls only adjust the level.

    json_mode: True emits structured JSON lines with the active trace id
    stamped on every record; None reads TPUMOUNTER_LOG_FORMAT ("json"
    enables it).
    """
    global _INITIALIZED
    if json_mode is None:
        json_mode = os.environ.get(
            "TPUMOUNTER_LOG_FORMAT", "console").strip().lower() == "json"
    root = logging.getLogger("gpumounter_tpu")
    with _LOCK:
        if _INITIALIZED:
            root.setLevel(level)
            return root
        root.setLevel(level)
        formatter = _make_formatter(json_mode)
        # Filter lives on the HANDLERS: child-logger records propagate
        # to root's handlers without running root's logger-level filters.
        trace_filter = _TraceIdFilter()
        stream = logging.StreamHandler(sys.stdout)
        stream.setFormatter(formatter)
        stream.addFilter(trace_filter)
        root.addHandler(stream)
        if log_dir and filename:
            try:
                os.makedirs(log_dir, exist_ok=True)
                fileh = logging.FileHandler(os.path.join(log_dir, filename))
                fileh.setFormatter(formatter)
                fileh.addFilter(trace_filter)
                root.addHandler(fileh)
            except OSError:
                root.warning("cannot open log file in %s; stdout only", log_dir)
        root.propagate = False
        _INITIALIZED = True
    return root


def get_logger(name: str) -> logging.Logger:
    """Child logger under the gpumounter_tpu root."""
    return logging.getLogger("gpumounter_tpu").getChild(name)
