"""Structured logging for master/worker daemons.

Reference parity: pkg/util/log/log.go:9-30 (zap SugaredLogger, console encoder,
ISO8601 timestamps, Debug level, dual sink stdout + /var/log/GPUMounter/<file>.log).
Here: stdlib logging with an ISO8601 console formatter and optional file sink.
"""

from __future__ import annotations

import logging
import os
import sys
import threading

_LOCK = threading.Lock()
_INITIALIZED = False

_FMT = "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s"
_DATEFMT = "%Y-%m-%dT%H:%M:%S%z"


def init_logger(log_dir: str | None = None, filename: str | None = None,
                level: int = logging.DEBUG) -> logging.Logger:
    """Initialise root logging: stdout always; file sink if log_dir given.

    Mirrors InitLogger(log.go:9-17): distinct filenames per daemon
    ("tpumounter-master.log" / "tpumounter-worker.log"), multi-sink.
    Safe to call more than once; later calls only adjust the level.
    """
    global _INITIALIZED
    root = logging.getLogger("gpumounter_tpu")
    with _LOCK:
        if _INITIALIZED:
            root.setLevel(level)
            return root
        root.setLevel(level)
        formatter = logging.Formatter(_FMT, datefmt=_DATEFMT)
        stream = logging.StreamHandler(sys.stdout)
        stream.setFormatter(formatter)
        root.addHandler(stream)
        if log_dir and filename:
            try:
                os.makedirs(log_dir, exist_ok=True)
                fileh = logging.FileHandler(os.path.join(log_dir, filename))
                fileh.setFormatter(formatter)
                root.addHandler(fileh)
            except OSError:
                root.warning("cannot open log file in %s; stdout only", log_dir)
        root.propagate = False
        _INITIALIZED = True
    return root


def get_logger(name: str) -> logging.Logger:
    """Child logger under the gpumounter_tpu root."""
    return logging.getLogger("gpumounter_tpu").getChild(name)
