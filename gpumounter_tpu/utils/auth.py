"""Control-plane authentication: a per-deploy shared bearer secret.

The reference ships an UNAUTHENTICATED control plane — the master dials
the worker over an insecure channel (cmd/GPUMounter-master/main.go:82)
and its own HTTP API has no credential check at all — yet
`removegpu .../force/true` kills PIDs inside the target container. Any
in-cluster peer could kill a tenant's trainer. This module closes that:

  * one shared secret per deploy (a k8s Secret, or a projected SA token
    file), surfaced via TPUMOUNTER_AUTH_TOKEN / TPUMOUNTER_AUTH_TOKEN_FILE;
  * worker gRPC requires `authorization: Bearer <secret>` metadata on
    every mount RPC (the gRPC health service stays open for probes);
  * master HTTP requires `Authorization: Bearer <secret>` on every
    state-changing or topology-revealing route (/healthz, /metrics and
    the index stay open — read-only liveness/scrape surfaces);
  * running without a secret is an EXPLICIT opt-in
    (TPUMOUNTER_AUTH=insecure); in the default "token" mode a daemon
    with no secret refuses to start rather than serving open.

Comparisons are constant-time (hmac.compare_digest).
"""

from __future__ import annotations

import hmac

from gpumounter_tpu.utils.log import get_logger

logger = get_logger("auth")

AUTH_MODE_TOKEN = "token"
AUTH_MODE_INSECURE = "insecure"


class AuthConfigError(Exception):
    """The daemon's auth configuration is unusable (fail-closed)."""


def resolve_token(cfg) -> str | None:
    """The effective shared secret, or None when none is configured.

    TPUMOUNTER_AUTH_TOKEN (direct value) wins over
    TPUMOUNTER_AUTH_TOKEN_FILE (path — the deploy manifests mount the
    k8s Secret there). File contents are stripped of trailing newlines.
    """
    if getattr(cfg, "auth_token", ""):
        return cfg.auth_token
    path = getattr(cfg, "auth_token_file", "")
    if path:
        try:
            with open(path, encoding="utf-8") as f:
                token = f.read().strip()
        except OSError as exc:
            raise AuthConfigError(
                f"auth token file {path!r} unreadable: {exc}") from exc
        if not token:
            raise AuthConfigError(f"auth token file {path!r} is empty")
        return token
    return None


def resolve_read_token(cfg) -> str | None:
    """Optional read-only scope secret for observability routes
    (/metrics, /audit, /trace): scrapers and dashboards present this
    token and can read, never mutate. None when not configured — the
    routes then keep their legacy behavior (metrics open; audit/trace
    gated on the mutate token). The mutate token always implies read."""
    if getattr(cfg, "auth_read_token", ""):
        return cfg.auth_read_token
    path = getattr(cfg, "auth_read_token_file", "")
    if path:
        try:
            with open(path, encoding="utf-8") as f:
                token = f.read().strip()
        except OSError as exc:
            raise AuthConfigError(
                f"read token file {path!r} unreadable: {exc}") from exc
        if not token:
            raise AuthConfigError(f"read token file {path!r} is empty")
        return token
    return None


def required_token(cfg, role: str) -> str | None:
    """Fail-closed startup resolution for a daemon.

    Returns the secret in "token" mode, None in explicit "insecure"
    mode; raises AuthConfigError when "token" mode has no secret or the
    mode is unrecognized. `role` only labels log/error messages.
    """
    mode = getattr(cfg, "auth_mode", AUTH_MODE_TOKEN) or AUTH_MODE_TOKEN
    if mode == AUTH_MODE_INSECURE:
        logger.warning(
            "%s starting with TPUMOUNTER_AUTH=insecure: the control plane "
            "will accept requests from ANY in-cluster peer (force-remove "
            "kills tenant PIDs) — use only in trusted dev environments",
            role)
        return None
    if mode != AUTH_MODE_TOKEN:
        raise AuthConfigError(
            f"unknown TPUMOUNTER_AUTH mode {mode!r} "
            f"(expected {AUTH_MODE_TOKEN!r} or {AUTH_MODE_INSECURE!r})")
    token = resolve_token(cfg)
    if not token:
        raise AuthConfigError(
            f"{role}: TPUMOUNTER_AUTH=token (the default) but neither "
            f"TPUMOUNTER_AUTH_TOKEN nor TPUMOUNTER_AUTH_TOKEN_FILE is "
            f"set; set one (deploy.sh generates a Secret) or opt in to "
            f"TPUMOUNTER_AUTH=insecure explicitly")
    return token


def check_bearer(header_value: str | None, token: str) -> bool:
    """Constant-time check of an `Authorization: Bearer <x>` value."""
    if not header_value:
        return False
    scheme, _, presented = header_value.partition(" ")
    if scheme.lower() != "bearer":
        return False
    # Compare as bytes: compare_digest raises TypeError on non-ASCII
    # str, which would turn a garbage header (latin-1 from http.server)
    # into a 500 instead of a 401. surrogateescape keeps arbitrary
    # attacker bytes encodable.
    return hmac.compare_digest(
        presented.strip().encode("utf-8", "surrogateescape"),
        token.encode("utf-8", "surrogateescape"))
