"""Single import point for grpc, deferred until first attribute access.

Policy
------
grpc's cython core registers pthread_atfork handlers at import time.
Subprocess/fork-heavy paths (worker/mounter.py, nsutil/, the bench
harnesses) must be importable without pulling grpc — and with it those
handlers — into the process. Therefore **no module in gpumounter_tpu
imports grpc at module top**. Every user does

    from gpumounter_tpu.utils.lazy_grpc import grpc

and the real module loads on the first attribute access, i.e. when a
channel or server is actually constructed — by which point the process
has committed to being a gRPC endpoint. Enforced by
tests/test_lazy_grpc.py (imports the mounter in a subprocess and asserts
"grpc" never enters sys.modules).

Reference contrast: the reference links grpc unconditionally in both
binaries (cmd/GPUMounter-worker/main.go:24-33); it can afford to because
Go gRPC has no fork-handler hazard. Python grpcio does, hence the policy.
"""

from __future__ import annotations

import importlib
from typing import Any


class _LazyGrpc:
    """Attribute-forwarding proxy; imports grpc exactly once, on demand."""

    _module = None

    def _load(self):
        if _LazyGrpc._module is None:
            _LazyGrpc._module = importlib.import_module("grpc")
        return _LazyGrpc._module

    def __getattr__(self, name: str) -> Any:
        return getattr(self._load(), name)


grpc = _LazyGrpc()
