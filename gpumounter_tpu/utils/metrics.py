"""Minimal Prometheus text-format metrics registry.

The reference exposes no metrics (SURVEY.md §5). Both daemons here serve
/metrics with counters and histograms for mount/unmount operations and their
phase latencies. Implemented on stdlib only (no prometheus_client in image).

Thread-safety contract (audited for the MOUNT_CONCURRENCY fan-out, where
mount_many's inject pool and the gRPC handler threads observe/inc the same
instruments concurrently while scrapes render): every mutation of an
instrument's samples — inc/set/observe, exemplar capture included — and
every read — collect/snapshot/get — happens under that instrument's own
lock; Registry mutations (register) and render's metric-list copy happen
under the registry lock. Nothing touches `_values`/`_counts` outside a
lock. tests/test_metrics.py stress-proves the histogram under a
thread-pool of concurrent observers racing a renderer.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from gpumounter_tpu.utils.locks import OrderedLock

#: The bounded label-key vocabulary. Every label key used on any
#: instrument must come from this set (tpulint rule metrics-discipline)
#: — label VALUES are budgeted by tests/test_metrics_cardinality.py,
#: label KEYS are budgeted here. Adding a key is a reviewed decision:
#: each one multiplies the worst-case series count, so the addition
#: must say what bounds its value domain.
ALLOWED_LABEL_KEYS = frozenset({
    "endpoint",   # k8s API endpoint (bounded by the client surface)
    "from_state",  # quarantine transition source (health STATES, 4 values)
    "kind",       # record/read kind (bounded enums per subsystem)
    "method",     # RPC method name (bounded by the proto surface)
    "name",       # failpoint site name (bounded by faults/registry.py)
    "node",       # node name (budgeted: fleet-scoped series only)
    "objective",  # SLO objective id (bounded by config)
    "outcome",    # operation outcome enum
    "phase",      # mount/migration phase enum
    "reason",     # failure-reason enum
    "result",     # success/error result enum
    "state",      # health-state enum
    "to_state",   # quarantine transition target (health STATES, 4 values;
                  # with from_state ≤16 series — test_metrics_cardinality
                  # budgets the plane)
    "window",     # SLO burn window (bounded by config)
    "worker",     # worker address (budgeted: fleet-scoped series only)
})


def _fmt_float(value: float) -> str:
    """Prometheus-style bucket bound: integral bounds render bare
    ("1", "30"), everything else as the shortest float repr."""
    if float(value) == int(value):
        return str(int(value))
    return repr(float(value))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


@dataclass
class Counter:
    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: OrderedLock = field(
        default_factory=lambda: OrderedLock("metrics.counter"))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def snapshot(self) -> dict[tuple, float]:
        """Labels-tuple -> value copy (the fleet telemetry reader)."""
        with self._lock:
            return dict(self._values)

    def total(self) -> float:
        """Sum across every labelset."""
        with self._lock:
            return sum(self._values.values())

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            if not self._values:
                lines.append(f"{self.name} 0")
            for key, val in sorted(self._values.items()):
                lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


@dataclass
class Gauge:
    """A value that can go up and down (queue depths, registered counts).

    Unlike Counter, an unlabeled gauge renders 0 until first set so
    scrapers see the series exist from process start.
    """

    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: OrderedLock = field(
        default_factory=lambda: OrderedLock("metrics.gauge"))

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def get(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def snapshot(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._values)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            if not self._values:
                lines.append(f"{self.name} 0")
            for key, val in sorted(self._values.items()):
                lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0)


def estimate_quantile(buckets: tuple, counts: list, q: float) -> float:
    """Quantile estimate from cumulative bucket counts (the standard
    Prometheus histogram_quantile linear interpolation). `counts` is the
    per-bucket cumulative count list with the +Inf total last; returns
    seconds (the last finite bound when the quantile lands in +Inf)."""
    total = counts[-1]
    if total <= 0:
        return 0.0
    rank = q * total
    prev_bound, prev_count = 0.0, 0
    for i, bound in enumerate(buckets):
        if counts[i] >= rank:
            span = counts[i] - prev_count
            if span <= 0:
                return bound
            return prev_bound + (bound - prev_bound) * (rank - prev_count) / span
        prev_bound, prev_count = bound, counts[i]
    return float(buckets[-1]) if buckets else 0.0


@dataclass
class Histogram:
    name: str
    help: str
    buckets: tuple = _DEFAULT_BUCKETS
    #: labels-tuple -> [cumulative counts (+Inf last), sum,
    #:                  {bucket index -> (trace_id, value, unix ts)}]
    _counts: dict[tuple, list] = field(default_factory=dict)
    _lock: OrderedLock = field(
        default_factory=lambda: OrderedLock("metrics.histogram"))

    def observe(self, value: float, trace_id: str = "",
                **labels: str) -> None:
        """Record one observation. `trace_id` (optional) attaches an
        OpenMetrics-style exemplar to the bucket the value lands in —
        the join key from a latency outlier back to its distributed
        trace (`tpumounter trace <id>`). Exemplars are last-write-wins
        per bucket and ride the same lock as the counts."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            entry = self._counts.setdefault(
                key, [[0] * (len(self.buckets) + 1), 0.0, {}])
            counts = entry[0]
            bucket_idx = len(self.buckets)  # +Inf
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    bucket_idx = min(bucket_idx, i)
            counts[-1] += 1  # +Inf
            entry[1] += value
            if trace_id:
                entry[2][bucket_idx] = (trace_id, value,
                                        round(time.time(), 3))

    def snapshot(self) -> dict[tuple, dict]:
        """Labels-tuple -> {"counts": [...], "sum": float, "exemplars":
        {bucket index: (trace_id, value, ts)}} deep copy."""
        with self._lock:
            return {key: {"counts": list(entry[0]), "sum": entry[1],
                          "exemplars": dict(entry[2])}
                    for key, entry in self._counts.items()}

    def quantile(self, q: float, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            entry = self._counts.get(key)
            counts = list(entry[0]) if entry else []
        if not counts:
            return 0.0
        return estimate_quantile(self.buckets, counts, q)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()

    def collect(self, openmetrics: bool = False) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, (counts, total, exemplars) in sorted(self._counts.items()):
                labels = dict(key)
                for i, b in enumerate(self.buckets):
                    line = (f"{self.name}_bucket"
                            f"{_fmt_labels({**labels, 'le': _fmt_float(b)})} "
                            f"{counts[i]}")
                    lines.append(self._with_exemplar(
                        line, exemplars.get(i), openmetrics))
                inf_line = (f"{self.name}_bucket"
                            f"{_fmt_labels({**labels, 'le': '+Inf'})} "
                            f"{counts[-1]}")
                lines.append(self._with_exemplar(
                    inf_line, exemplars.get(len(self.buckets)), openmetrics))
                lines.append(f"{self.name}_sum{_fmt_labels(labels)} {total}")
                lines.append(f"{self.name}_count{_fmt_labels(labels)} {counts[-1]}")
        return lines

    @staticmethod
    def _with_exemplar(line: str, exemplar, openmetrics: bool) -> str:
        """OpenMetrics exemplar suffix — only in openmetrics renders; the
        classic text/plain;version=0.0.4 exposition stays byte-clean for
        strict parsers."""
        if not openmetrics or exemplar is None:
            return line
        trace_id, value, ts = exemplar
        return f'{line} # {{trace_id="{trace_id}"}} {value} {ts}'


class Registry:
    def __init__(self) -> None:
        self._metrics: list = []
        self._lock = OrderedLock("metrics.registry")

    def counter(self, name: str, help: str) -> Counter:
        c = Counter(name, help)
        with self._lock:
            self._metrics.append(c)
        return c

    def gauge(self, name: str, help: str) -> Gauge:
        g = Gauge(name, help)
        with self._lock:
            self._metrics.append(g)
        return g

    def histogram(self, name: str, help: str, buckets: tuple = _DEFAULT_BUCKETS) -> Histogram:
        h = Histogram(name, help, buckets)
        with self._lock:
            self._metrics.append(h)
        return h

    def register(self, metric) -> None:
        """Add a custom collector: any object with name, collect() ->
        list[str], and reset(). Used by adapters whose samples live
        outside this module (the eBPF device-access telemetry table)."""
        with self._lock:
            self._metrics.append(metric)

    def find(self, name: str):
        """The registered instrument with this name, or None. Lets the
        fleet telemetry reader consume instruments by exposition name
        without importing the modules that own them (a master-side
        reader must not drag in worker-only modules)."""
        with self._lock:
            for m in self._metrics:
                if getattr(m, "name", None) == name:
                    return m
        return None

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition. `openmetrics=True` additionally
        stamps histogram bucket lines with their trace-id exemplars
        (served when the scraper negotiates application/openmetrics-text
        via Accept)."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            if openmetrics and isinstance(m, Histogram):
                lines.extend(m.collect(openmetrics=True))
            else:
                lines.extend(m.collect())
        return "\n".join(lines) + "\n"

    def series_count(self) -> int:
        """Number of exposed sample lines (non-comment) — the CI
        cardinality guard's measure of exposition size."""
        return sum(1 for line in self.render().splitlines()
                   if line and not line.startswith("#"))

    def reset_all(self) -> None:
        """Zero every registered metric's samples (the instruments stay
        registered — module-level handles keep working). Test hook: the
        conftest fixture calls this between tests so exposition tests
        cannot bleed counters across the suite."""
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            m.reset()


REGISTRY = Registry()

MOUNT_TOTAL = REGISTRY.counter(
    "tpumounter_mount_total", "Total mount operations by result")
UNMOUNT_TOTAL = REGISTRY.counter(
    "tpumounter_unmount_total", "Total unmount operations by result")
MOUNT_LATENCY = REGISTRY.histogram(
    "tpumounter_mount_latency_seconds", "End-to-end hot-mount latency")
PHASE_LATENCY = REGISTRY.histogram(
    "tpumounter_phase_latency_seconds", "Per-phase latency (phase label)")
MOUNT_ROLLBACK_FAILURES = REGISTRY.counter(
    "tpumounter_mount_rollback_failures_total",
    "Failed grant undos during mount rollback — each one is a leaked "
    "cgroup grant needing operator attention")
