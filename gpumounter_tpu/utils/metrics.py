"""Minimal Prometheus text-format metrics registry.

The reference exposes no metrics (SURVEY.md §5). Both daemons here serve
/metrics with counters and histograms for mount/unmount operations and their
phase latencies. Implemented on stdlib only (no prometheus_client in image).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


def _fmt_float(value: float) -> str:
    """Prometheus-style bucket bound: integral bounds render bare
    ("1", "30"), everything else as the shortest float repr."""
    if float(value) == int(value):
        return str(int(value))
    return repr(float(value))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


@dataclass
class Counter:
    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            if not self._values:
                lines.append(f"{self.name} 0")
            for key, val in sorted(self._values.items()):
                lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


@dataclass
class Gauge:
    """A value that can go up and down (queue depths, registered counts).

    Unlike Counter, an unlabeled gauge renders 0 until first set so
    scrapers see the series exist from process start.
    """

    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def get(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            if not self._values:
                lines.append(f"{self.name} 0")
            for key, val in sorted(self._values.items()):
                lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0)


@dataclass
class Histogram:
    name: str
    help: str
    buckets: tuple = _DEFAULT_BUCKETS
    _counts: dict[tuple, list] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            entry = self._counts.setdefault(key, [[0] * (len(self.buckets) + 1), 0.0])
            counts, _ = entry
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1  # +Inf
            entry[1] += value

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, (counts, total) in sorted(self._counts.items()):
                labels = dict(key)
                for i, b in enumerate(self.buckets):
                    lines.append(
                        f"{self.name}_bucket{_fmt_labels({**labels, 'le': _fmt_float(b)})} {counts[i]}"
                    )
                lines.append(f"{self.name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} {counts[-1]}")
                lines.append(f"{self.name}_sum{_fmt_labels(labels)} {total}")
                lines.append(f"{self.name}_count{_fmt_labels(labels)} {counts[-1]}")
        return lines


class Registry:
    def __init__(self) -> None:
        self._metrics: list = []
        self._lock = threading.Lock()

    def counter(self, name: str, help: str) -> Counter:
        c = Counter(name, help)
        with self._lock:
            self._metrics.append(c)
        return c

    def gauge(self, name: str, help: str) -> Gauge:
        g = Gauge(name, help)
        with self._lock:
            self._metrics.append(g)
        return g

    def histogram(self, name: str, help: str, buckets: tuple = _DEFAULT_BUCKETS) -> Histogram:
        h = Histogram(name, help, buckets)
        with self._lock:
            self._metrics.append(h)
        return h

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"

    def reset_all(self) -> None:
        """Zero every registered metric's samples (the instruments stay
        registered — module-level handles keep working). Test hook: the
        conftest fixture calls this between tests so exposition tests
        cannot bleed counters across the suite."""
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            m.reset()


REGISTRY = Registry()

MOUNT_TOTAL = REGISTRY.counter(
    "tpumounter_mount_total", "Total mount operations by result")
UNMOUNT_TOTAL = REGISTRY.counter(
    "tpumounter_unmount_total", "Total unmount operations by result")
MOUNT_LATENCY = REGISTRY.histogram(
    "tpumounter_mount_latency_seconds", "End-to-end hot-mount latency")
PHASE_LATENCY = REGISTRY.histogram(
    "tpumounter_phase_latency_seconds", "Per-phase latency (phase label)")
MOUNT_ROLLBACK_FAILURES = REGISTRY.counter(
    "tpumounter_mount_rollback_failures_total",
    "Failed grant undos during mount rollback — each one is a leaked "
    "cgroup grant needing operator attention")
