"""Per-phase latency instrumentation.

The reference has zero timing visibility (SURVEY.md §5: only zap log
timestamps). Our north-star metric is hot-mount latency (BASELINE.json), so
every mount/unmount records a phase breakdown: slave-pod schedule, cgroup
grant, device-file inject, JAX-visible.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@dataclass
class PhaseTimer:
    """Accumulates named phase durations for one operation."""

    phases: dict[str, float] = field(default_factory=dict)
    _start: float = field(default_factory=time.monotonic)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + (time.monotonic() - t0)

    def record(self, name: str, seconds: float) -> None:
        self.phases[name] = self.phases.get(name, 0.0) + seconds

    def total(self) -> float:
        return time.monotonic() - self._start

    def summary_ms(self) -> dict[str, float]:
        out = {k: round(v * 1000.0, 3) for k, v in self.phases.items()}
        out["total"] = round(self.total() * 1000.0, 3)
        return out
