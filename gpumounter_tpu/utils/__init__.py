from gpumounter_tpu.utils.log import get_logger, init_logger

__all__ = ["get_logger", "init_logger"]
