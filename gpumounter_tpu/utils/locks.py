"""Named, order-recorded locks: the runtime half of the tpulint
lock-order deadlock check.

tools/tpulint/lockorder.py extracts the STATIC nesting graph of lock
regions (``with self._lock:`` blocks, one node per lock name) from the
source tree and fails CI on cycles. Static analysis alone misses orders
that only materialize through indirection (callbacks, threads started
under a lock, data-driven dispatch) — so the hottest lock graph is also
instrumented: modules migrated to :class:`OrderedLock` /
:class:`OrderedCondition` record every *observed* nested acquisition
(outer-name -> inner-name) into a process-global
:class:`LockOrderRecorder`. The chaos harness asserts after every
scenario that the dynamic edge set is acyclic AND consistent with the
static graph (invariant 15), and exports the trace for CI
(``TPM_LOCK_TRACE``), so a runtime order contradicting the reviewed
static graph fails the build instead of deadlocking a master at 3am.

Node identity is the lock NAME, not the instance: every
``OrderedLock("metrics.counter")`` is one node. Two instances of the
same name nested inside each other therefore collapse to a self-edge —
recorded separately (``same_name_nestings``) and excluded from cycle
detection, because name-level analysis cannot order instances. Keep
same-named locks leaf-level (the metrics instruments are the pattern).

Stdlib-only and import-light on purpose: this module is imported by
utils/metrics.py, which the mount path imports (lazy-grpc policy).
"""

from __future__ import annotations

import threading

__all__ = [
    "OrderedLock",
    "OrderedCondition",
    "LockOrderRecorder",
    "LockOrderViolation",
    "RECORDER",
    "find_cycle",
    "held_locks",
]


class LockOrderViolation(AssertionError):
    """The observed acquisition orders admit a deadlock (a cycle), or
    contradict the statically-extracted nesting graph."""


def find_cycle(edges) -> list[str] | None:
    """First cycle in a directed graph given as (src, dst) pairs, as a
    node path ``[a, b, ..., a]``; None when acyclic. Self-edges are the
    caller's business — this reports them as ``[a, a]``."""
    graph: dict[str, list[str]] = {}
    for src, dst in edges:
        graph.setdefault(src, []).append(dst)
    WHITE, GREY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    parent: dict[str, str] = {}
    for root in sorted(graph):
        if color.get(root, WHITE) != WHITE:
            continue
        stack: list[tuple[str, int]] = [(root, 0)]
        color[root] = GREY
        while stack:
            node, idx = stack[-1]
            neighbours = graph.get(node, [])
            if idx >= len(neighbours):
                color[node] = BLACK
                stack.pop()
                continue
            stack[-1] = (node, idx + 1)
            nxt = neighbours[idx]
            state = color.get(nxt, WHITE)
            if state == GREY:
                if nxt == node:
                    return [node, node]
                # Walk parents back from `node` to `nxt`, then close.
                path = [node]
                cur = node
                while cur != nxt:
                    cur = parent[cur]
                    path.append(cur)
                path.reverse()
                return path + [nxt]
            if state == WHITE:
                color[nxt] = GREY
                parent[nxt] = node
                stack.append((nxt, 0))
    return None


class LockOrderRecorder:
    """Process-global observed-nesting ledger.

    Per-thread held-lock stacks live in a threading.local; each first
    observation of (outer-name, inner-name) lands in ``_edges`` with the
    thread name and full held stack that witnessed it — the evidence a
    violation report prints. The guard is a plain threading.Lock (an
    OrderedLock here would recurse into its own bookkeeping).
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        #: (outer, inner) -> {"thread": ..., "stack": [...]} first witness
        self._edges: dict[tuple[str, str], dict] = {}
        #: names seen nested inside a region of the SAME name
        self._same_name: dict[str, dict] = {}
        self._tls = threading.local()

    # --- per-thread stack ---

    def _stack(self) -> list[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def note_acquired(self, name: str) -> None:
        stack = self._stack()
        if stack:
            outer = stack[-1]
            if outer == name:
                if name not in self._same_name:
                    with self._mu:
                        self._same_name.setdefault(
                            name, {"thread": threading.current_thread().name,
                                   "stack": list(stack)})
            else:
                key = (outer, name)
                if key not in self._edges:  # racy fast-path; mu settles it
                    with self._mu:
                        self._edges.setdefault(
                            key, {"thread": threading.current_thread().name,
                                  "stack": list(stack) + [name]})
        stack.append(name)

    def note_released(self, name: str) -> None:
        stack = self._stack()
        # LIFO in the `with` discipline; tolerate out-of-order release.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # --- reads ---

    def edges(self) -> set[tuple[str, str]]:
        with self._mu:
            return set(self._edges)

    def witnesses(self) -> dict[tuple[str, str], dict]:
        with self._mu:
            return {k: dict(v) for k, v in self._edges.items()}

    def same_name_nestings(self) -> set[str]:
        with self._mu:
            return set(self._same_name)

    def dump(self) -> dict:
        """JSON-shaped export (the chaos lane's TPM_LOCK_TRACE artifact;
        ``python -m tools.tpulint --verify-dynamic`` consumes it)."""
        with self._mu:
            return {
                "edges": sorted([list(k) for k in self._edges]),
                "witnesses": {f"{a}->{b}": dict(w)
                              for (a, b), w in sorted(self._edges.items())},
                "same_name_nestings": sorted(self._same_name),
            }

    def clear(self) -> None:
        with self._mu:
            self._edges.clear()
            self._same_name.clear()

    # --- validation ---

    def assert_consistent(self, static_edges=None) -> None:
        """Raise LockOrderViolation when the observed edges contain a
        cycle, or — given the static nesting graph — when combining the
        two produces one (an observed order the static graph forbids).
        """
        observed = self.edges()
        cycle = find_cycle(observed)
        if cycle is not None:
            raise LockOrderViolation(
                "observed lock acquisitions form a cycle (potential "
                f"deadlock): {' -> '.join(cycle)}; witnesses: "
                f"{self._cycle_witnesses(cycle)}")
        if static_edges:
            combined = observed | {tuple(e) for e in static_edges
                                   if e[0] != e[1]}
            cycle = find_cycle(combined)
            if cycle is not None:
                dynamic = [e for e in zip(cycle, cycle[1:])
                           if e in observed]
                raise LockOrderViolation(
                    "observed acquisition order contradicts the static "
                    f"lock graph: cycle {' -> '.join(cycle)} (observed "
                    f"edges in it: {dynamic}; witnesses: "
                    f"{self._cycle_witnesses(cycle)})")

    def _cycle_witnesses(self, cycle: list[str]) -> dict:
        pairs = set(zip(cycle, cycle[1:]))
        with self._mu:
            return {f"{a}->{b}": self._edges[(a, b)]["stack"]
                    for (a, b) in pairs if (a, b) in self._edges}


RECORDER = LockOrderRecorder()


def held_locks() -> list[str]:
    """This thread's currently-held OrderedLock names, outermost first
    (a debugging/assertion hook for tests)."""
    return list(RECORDER._stack())


class OrderedLock:
    """A named threading.Lock that records observed nesting into the
    global RECORDER. Drop-in for the ``with lock:`` / acquire/release
    discipline; the name is the node id in the lock-order graph."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        if not name:
            raise ValueError("OrderedLock needs a non-empty name")
        self.name = name
        self._inner = self._factory()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            RECORDER.note_acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        RECORDER.note_released(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<OrderedLock {self.name!r}>"


class OrderedCondition:
    """A named threading.Condition (RLock-backed, like the bare
    constructor) with the same nesting bookkeeping. ``wait`` fully
    releases the underlying lock, so the held-stack entry (or entries,
    under reentrant acquisition) is removed for the wait's duration and
    restored — with re-recorded edges — on wakeup."""

    def __init__(self, name: str):
        if not name:
            raise ValueError("OrderedCondition needs a non-empty name")
        self.name = name
        self._inner = threading.Condition()

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            RECORDER.note_acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        RECORDER.note_released(self.name)

    def __enter__(self) -> "OrderedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: float | None = None) -> bool:
        held = self._drop_all()
        try:
            return self._inner.wait(timeout)
        finally:
            self._restore(held)

    def wait_for(self, predicate, timeout: float | None = None):
        # Delegating to the inner wait_for would bypass our wait()'s
        # stack bookkeeping; re-implement on top of self.wait.
        import time as _time
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = _time.monotonic() + timeout
                waittime = endtime - _time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def _drop_all(self) -> int:
        """Remove every reentrant held-stack entry for this name (wait
        releases the RLock completely); returns the count to restore."""
        stack = RECORDER._stack()
        count = stack.count(self.name)
        for _ in range(count):
            RECORDER.note_released(self.name)
        return count

    def _restore(self, count: int) -> None:
        for _ in range(count):
            RECORDER.note_acquired(self.name)

    def __repr__(self) -> str:
        return f"<OrderedCondition {self.name!r}>"
