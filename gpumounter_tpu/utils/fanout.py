"""Shared bounded fan-out core for the master's hot parallel paths.

Before this module every fan-out owned a fixed-width pool: the fleet
collector built a fresh 16-thread ThreadPoolExecutor per collect pass,
the recovery controller another per probe pass, bulk mounts spawned a
thread wave per node group and the canary prober ran serially. At 1k
nodes that is merely wasteful; at 10k nodes a collect pass serializes
10k worker RPCs behind 16 threads while three other subsystems do the
same thing next to it with their own 16.

One process-wide executor replaces them:

  * width sized to the host (cfg.fanout_width, 0 = auto), shared by
    collect / probe / bulk dispatch / canary — a pass's parallelism is
    no longer its private constant,
  * per-shard concurrency budgets (cfg.fanout_shard_budget): within one
    run() call, items mapping to the same shard occupy at most
    budget slots, so one slow rack cannot camp the whole core and
    stall an unrelated shard's work,
  * order-preserving results with the submitting pass's error
    semantics (first exception re-raised after the pass drains, like
    the `pool.map` the call sites used),
  * re-entrancy safe: a task that itself fans out (a proxied bulk
    sub-batch mounting locally) falls back to transient threads
    instead of submitting to the pool it is running on — the classic
    nested-executor starvation deadlock cannot happen.

The instruments stay fleet-scalar: tasks by the bounded kind
vocabulary, plus one in-flight gauge. Node names never become labels.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from concurrent import futures

from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("fanout")

FANOUT_TASKS = REGISTRY.counter(
    "tpumounter_fanout_tasks_total",
    "tasks executed on the shared fan-out core, by kind")
FANOUT_INFLIGHT = REGISTRY.gauge(
    "tpumounter_fanout_inflight",
    "tasks currently running on the shared fan-out core")
FANOUT_SHARD_WAITS = REGISTRY.counter(
    "tpumounter_fanout_shard_waits_total",
    "task submissions parked behind a per-shard concurrency budget")


def _auto_width() -> int:
    return max(32, 4 * (os.cpu_count() or 8))


class FanoutCore:
    """One bounded executor shared by every master fan-out path."""

    def __init__(self, cfg=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.width = int(cfg.fanout_width) or _auto_width()
        self.shard_budget = int(cfg.fanout_shard_budget)
        self._pool = futures.ThreadPoolExecutor(
            max_workers=self.width, thread_name_prefix="fanout-core")
        self._in_core = threading.local()

    # --- plumbing ---

    def _call(self, fn, item, kind: str):
        self._in_core.active = True
        FANOUT_INFLIGHT.inc()
        try:
            return fn(item)
        finally:
            FANOUT_INFLIGHT.dec()
            FANOUT_TASKS.inc(kind=kind)
            self._in_core.active = False

    def _nested(self) -> bool:
        return bool(getattr(self._in_core, "active", False))

    def submit(self, fn, item, *, kind: str = "task") -> futures.Future:
        """One task on the core (no shard budget — single submissions
        are the caller's own concurrency decision). Safe from a core
        task: falls back to a transient thread."""
        if self._nested():
            fut: futures.Future = futures.Future()

            def _run():
                try:
                    fut.set_result(self._call(fn, item, kind))
                except BaseException as exc:  # noqa: BLE001 — boundary
                    fut.set_exception(exc)

            threading.Thread(target=_run, daemon=True,
                             name="fanout-nested").start()
            return fut
        return self._pool.submit(self._call, fn, item, kind)

    def run(self, items, fn, *, kind: str = "task", shard_of=None,
            shard_budget: int | None = None) -> list:
        """fn(item) for every item, results in item order.

        shard_of(item) -> hashable names the item's shard; items of one
        shard hold at most shard_budget (default cfg) core slots at a
        time, so a stalled shard's tasks queue behind their budget
        while other shards keep flowing. The first exception re-raises
        after all items finish (pool.map parity — call sites that want
        per-item degradation catch inside fn)."""
        items = list(items)
        if not items:
            return []
        budget = self.shard_budget if shard_budget is None \
            else int(shard_budget)
        if self._nested():
            return self._run_transient(items, fn, kind, shard_of, budget)

        results: list = [None] * len(items)
        first_error: list[BaseException | None] = [None]
        inflight: dict[futures.Future, tuple[int, object]] = {}
        shard_load: dict[object, int] = {}
        waiting: dict[object, deque[int]] = {}

        def shard_key(i: int):
            if shard_of is None or budget <= 0:
                return None
            try:
                return shard_of(items[i])
            except Exception:  # noqa: BLE001 — a broken key fn must
                # not fail the pass; unkeyed items are unbudgeted
                return None

        def start(i: int, key) -> None:
            if key is not None:
                shard_load[key] = shard_load.get(key, 0) + 1
            inflight[self._pool.submit(self._call, fn, items[i],
                                       kind)] = (i, key)

        for i in range(len(items)):
            key = shard_key(i)
            if key is not None and shard_load.get(key, 0) >= budget:
                waiting.setdefault(key, deque()).append(i)
                FANOUT_SHARD_WAITS.inc()
            else:
                start(i, key)
        while inflight:
            done, _ = futures.wait(list(inflight),
                                   return_when=futures.FIRST_COMPLETED)
            for fut in done:
                i, key = inflight.pop(fut)
                try:
                    results[i] = fut.result()
                except BaseException as exc:  # noqa: BLE001 — drain
                    # the whole pass first, re-raise after (map parity)
                    if first_error[0] is None:
                        first_error[0] = exc
                if key is not None:
                    shard_load[key] -= 1
                    queue = waiting.get(key)
                    if queue:
                        start(queue.popleft(), key)
                        if not queue:
                            del waiting[key]
        if first_error[0] is not None:
            raise first_error[0]
        return results

    def _run_transient(self, items, fn, kind, shard_of, budget) -> list:
        """Nested-call fallback: bounded waves of transient threads
        (the pre-core shape) — never submits to the pool the caller is
        already running on."""
        results: list = [None] * len(items)
        errors: list = [None] * len(items)

        def _one(i: int) -> None:
            try:
                results[i] = self._call(fn, items[i], kind)
            except BaseException as exc:  # noqa: BLE001 — see run()
                errors[i] = exc

        width = max(1, budget if budget > 0 else self.width)
        for base in range(0, len(items), width):
            wave = [threading.Thread(target=_one, args=(i,), daemon=True,
                                     name="fanout-nested")
                    for i in range(base, min(base + width, len(items)))]
            for th in wave:
                th.start()
            for th in wave:
                th.join()
        for exc in errors:
            if exc is not None:
                raise exc
        return results

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


_CORE: FanoutCore | None = None
_CORE_MU = OrderedLock("fanout.core")


def get_core(cfg=None) -> FanoutCore:
    """The process-wide core (sized by the first caller's cfg — one
    process, one width, exactly like the metrics registry)."""
    global _CORE
    with _CORE_MU:
        if _CORE is None:
            _CORE = FanoutCore(cfg)
        return _CORE


def reset_core() -> None:
    """Tests/benches: drop the global so the next get_core() re-sizes
    from fresh config."""
    global _CORE
    with _CORE_MU:
        if _CORE is not None:
            _CORE.shutdown()
        _CORE = None
