"""FakeCluster: simulated TPU node(s), end-to-end testable in-process.

Wires together:
  * FakeDeviceBackend per node — fake chips in a tmp dir (null-backed char
    devices when privileged, regular files otherwise)
  * FakeKubeletServer per node — real gRPC pod-resources server on a unix
    socket
  * one shared FakeKubeClient — API-server fake whose scheduler hook
    emulates the GKE TPU device plugin: pods requesting `google.com/tpu`
    are placed on a node with free chips (honoring a
    kubernetes.io/hostname nodeSelector), get chips assigned atomically
    under a lock, are marked Running, and their claims appear in that
    node's fake kubelet; when no node fits, the pod goes Unschedulable —
    exactly the signal the allocator maps to InsufficientTPU (reference
    allocator.go:262-270). Deletion frees chips.

Single-node form is BASELINE configs 1/4; the multi-node form underpins
config 5 (pod-slice coordination across hosts).
"""

from __future__ import annotations

import os
import threading

from gpumounter_tpu.collector.podresources import FakeKubeletServer
from gpumounter_tpu.config import Config
from gpumounter_tpu.device.backend import FakeDeviceBackend
from gpumounter_tpu.k8s.fake import FakeKubeClient
from gpumounter_tpu.k8s.types import Pod


class _FakeNode:
    def __init__(self, root: str, name: str, n_chips: int,
                 kubelet_versions: tuple[str, ...]):
        self.name = name
        self.fake_device_dir = os.path.join(root, name, "host-dev")
        self.kubelet_socket = os.path.join(root, name, "kubelet.sock")
        os.makedirs(os.path.dirname(self.kubelet_socket), exist_ok=True)
        self.backend = FakeDeviceBackend.create(self.fake_device_dir, n_chips)
        self.kubelet = FakeKubeletServer(self.kubelet_socket,
                                         versions=kubelet_versions)
        # chip id (device-plugin view) -> (namespace, pod) or None
        self.assignment: dict[str, tuple[str, str] | None] = {
            str(d.index): None for d in self.backend.list_devices()}
        # chip ids killed via kill_chip: excluded from scheduling even
        # after their owner releases them (a dead chip never heals).
        self.dead: set[str] = set()

    def free_ids(self) -> list[str]:
        return sorted((cid for cid, o in self.assignment.items()
                       if o is None and cid not in self.dead), key=int)


class FakeCluster:
    def __init__(self, root: str, n_chips: int = 4,
                 node_name: str = "tpu-node-0",
                 nodes: dict[str, int] | None = None,
                 scheduler_delay_s: float = 0.0,
                 kubelet_versions: tuple[str, ...] = ("v1",),
                 cfg: Config | None = None):
        self.root = root
        if nodes is None:
            nodes = {node_name: n_chips}
        self._nodes = {name: _FakeNode(root, name, count, kubelet_versions)
                       for name, count in nodes.items()}
        self.node_name = next(iter(self._nodes))  # primary (single-node API)
        base = (cfg or Config()).replace(slave_pod_timeout_s=10.0)
        self.cfg = self.node_cfg(self.node_name, base)
        self._alloc_lock = threading.Lock()
        self.kube = FakeKubeClient(scheduler_hook=self._schedule,
                                   delete_hook=self._reap,
                                   scheduler_delay_s=scheduler_delay_s)

    # --- per-node views ---

    def node(self, name: str | None = None) -> _FakeNode:
        return self._nodes[name or self.node_name]

    def node_cfg(self, name: str | None = None,
                 base: Config | None = None) -> Config:
        node = self.node(name)
        return (base or self.cfg).replace(
            fake_device_dir=node.fake_device_dir,
            kubelet_socket=node.kubelet_socket,
            slave_pod_timeout_s=10.0)

    @property
    def backend(self):
        return self.node().backend

    @property
    def kubelet(self):
        return self.node().kubelet

    @property
    def node_names(self) -> list[str]:
        return list(self._nodes)

    # --- device-plugin + scheduler emulation ---

    def _tpu_request(self, pod: dict) -> int:
        return Pod(pod).resource_limit(self.cfg.tpu_resource_name)

    def _pick_node(self, pod: Pod, want: int) -> _FakeNode | None:
        """Placement honoring nodeSelector; else first node that fits.
        Caller holds _alloc_lock."""
        selector = (pod.obj.get("spec", {}).get("nodeSelector") or {}).get(
            "kubernetes.io/hostname")
        candidates = ([self._nodes[selector]]
                      if selector in self._nodes else
                      [] if selector else list(self._nodes.values()))
        for node in candidates:
            if len(node.free_ids()) >= want:
                return node
        return None

    def _schedule(self, pod: dict) -> None:
        p = Pod(pod)
        want = self._tpu_request(pod)
        if want == 0:
            selector = (pod.get("spec", {}).get("nodeSelector") or {}).get(
                "kubernetes.io/hostname")
            pod.setdefault("spec", {}).setdefault(
                "nodeName", selector or self.node_name)
            pod.setdefault("status", {})["phase"] = "Running"
            return
        with self._alloc_lock:
            node = self._pick_node(p, want)
            if node is None:
                pod.setdefault("status", {}).update({
                    "phase": "Pending",
                    "conditions": [{
                        "type": "PodScheduled", "status": "False",
                        "reason": "Unschedulable",
                        "message": f"0/{len(self._nodes)} nodes available: "
                                   f"insufficient "
                                   f"{self.cfg.tpu_resource_name}",
                    }]})
                return
            assigned = node.free_ids()[:want]
            for cid in assigned:
                node.assignment[cid] = (p.namespace, p.name)
            node.kubelet.set_claim(p.name, p.namespace,
                                   self.cfg.tpu_resource_name, assigned)
        pod.setdefault("spec", {})["nodeName"] = node.name
        pod.setdefault("status", {})["phase"] = "Running"

    def _reap(self, pod: dict) -> None:
        p = Pod(pod)
        with self._alloc_lock:
            for node in self._nodes.values():
                for cid, owner in list(node.assignment.items()):
                    if owner == (p.namespace, p.name):
                        node.assignment[cid] = None
                node.kubelet.claims = [
                    c for c in node.kubelet.claims
                    if not (c[0] == p.name and c[1] == p.namespace)]

    # --- fault injection ---

    def kill_chip(self, chip_id: int | str, node: str | None = None) -> None:
        """Mark one chip dead: the fake backend's health probe reports it
        unhealthy and the fake scheduler never assigns it again (real
        dead chips don't resurrect). Any current owner keeps its claim —
        exactly the state the elastic prober must detect and heal."""
        target = self.node(node)
        cid = str(chip_id)
        with self._alloc_lock:
            if cid not in target.assignment:
                raise KeyError(f"no chip {cid} on node {target.name}")
            target.dead.add(cid)
        target.backend.mark_dead(f"accel{cid}")

    # --- convenience ---

    def free_chip_count(self, node: str | None = None) -> int:
        with self._alloc_lock:
            if node is not None:
                return len(self._nodes[node].free_ids())
            return sum(len(n.free_ids()) for n in self._nodes.values())

    def add_target_pod(self, name: str, namespace: str = "default",
                       uid: str | None = None,
                       node: str | None = None) -> Pod:
        """A running workload pod (no TPU request) to hot-mount into."""
        manifest = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": namespace,
                         **({"uid": uid} if uid else {})},
            "spec": {"containers": [{"name": "main", "image": "app"}],
                     **({"nodeSelector": {"kubernetes.io/hostname": node}}
                        if node else {})},
        }
        self.kube.create_pod(namespace, manifest)
        # Running pods always carry a pod IP (the slice coordinator uses
        # it as the resolvable TPU_WORKER_HOSTNAMES entry).
        ip_suffix = (abs(hash((namespace, name))) % 250) + 2
        self.kube.set_pod_status(namespace, name, containerStatuses=[{
            "name": "main",
            "containerID": f"containerd://{name}-cid",
            "state": {"running": {}},
        }], podIP=f"10.8.0.{ip_suffix}")
        pod = self.kube.wait_for_pod(
            namespace, name,
            lambda pj: pj is not None and Pod(pj).phase == "Running",
            timeout_s=5.0)
        assert pod is not None, f"target pod {name} did not reach Running"
        return Pod(self.kube.get_pod(namespace, name))

    def start(self) -> "FakeCluster":
        for node in self._nodes.values():
            node.kubelet.start()
        return self

    def stop(self) -> None:
        for node in self._nodes.values():
            node.kubelet.stop()
