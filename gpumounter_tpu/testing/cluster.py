"""FakeCluster: one simulated TPU node, end-to-end testable in-process.

Wires together:
  * FakeDeviceBackend — N fake chips in a tmp dir (null-backed char devices
    when privileged, regular files otherwise)
  * FakeKubeletServer — real gRPC pod-resources server on a unix socket
  * FakeKubeClient — API-server fake whose scheduler hook emulates the GKE
    TPU device plugin: pods requesting `google.com/tpu` get free chips
    assigned (atomically, under a lock), are marked Running, and their
    claims appear in the fake kubelet; when chips run out the pod goes
    Unschedulable — exactly the signal the allocator maps to
    InsufficientTPU (reference allocator.go:262-270). Deletion frees chips.

This is the substrate for BASELINE configs 1 and 4 (dry-run and contended
add/remove) with no Kubernetes anywhere.
"""

from __future__ import annotations

import os
import threading

from gpumounter_tpu.collector.podresources import FakeKubeletServer
from gpumounter_tpu.config import Config
from gpumounter_tpu.device.backend import FakeDeviceBackend
from gpumounter_tpu.k8s.fake import FakeKubeClient
from gpumounter_tpu.k8s.types import Pod


class FakeCluster:
    def __init__(self, root: str, n_chips: int = 4,
                 node_name: str = "tpu-node-0",
                 scheduler_delay_s: float = 0.0,
                 kubelet_versions: tuple[str, ...] = ("v1",),
                 cfg: Config | None = None):
        self.root = root
        self.node_name = node_name
        self.cfg = (cfg or Config()).replace(
            fake_device_dir=os.path.join(root, "host-dev"),
            kubelet_socket=os.path.join(root, "kubelet.sock"),
            slave_pod_timeout_s=10.0,
        )
        self.backend = FakeDeviceBackend.create(self.cfg.fake_device_dir,
                                                n_chips)
        self.kubelet = FakeKubeletServer(self.cfg.kubelet_socket,
                                         versions=kubelet_versions)
        self._alloc_lock = threading.Lock()
        # chip id (device-plugin view) -> (namespace, pod) or None
        self._assignment: dict[str, tuple[str, str] | None] = {
            str(d.index): None for d in self.backend.list_devices()}
        self.kube = FakeKubeClient(scheduler_hook=self._schedule,
                                   delete_hook=self._reap,
                                   scheduler_delay_s=scheduler_delay_s)

    # --- device-plugin + scheduler emulation ---

    def _tpu_request(self, pod: dict) -> int:
        return Pod(pod).resource_limit(self.cfg.tpu_resource_name)

    def _schedule(self, pod: dict) -> None:
        p = Pod(pod)
        want = self._tpu_request(pod)
        if want == 0:
            pod.setdefault("spec", {}).setdefault("nodeName", self.node_name)
            pod.setdefault("status", {})["phase"] = "Running"
            return
        with self._alloc_lock:
            free = [cid for cid, owner in self._assignment.items()
                    if owner is None]
            if len(free) < want:
                pod.setdefault("status", {}).update({
                    "phase": "Pending",
                    "conditions": [{
                        "type": "PodScheduled", "status": "False",
                        "reason": "Unschedulable",
                        "message": f"0/1 nodes available: insufficient "
                                   f"{self.cfg.tpu_resource_name}",
                    }]})
                return
            assigned = sorted(free, key=int)[:want]
            for cid in assigned:
                self._assignment[cid] = (p.namespace, p.name)
            self.kubelet.set_claim(p.name, p.namespace,
                                   self.cfg.tpu_resource_name, assigned)
        pod.setdefault("spec", {})["nodeName"] = self.node_name
        pod.setdefault("status", {})["phase"] = "Running"

    def _reap(self, pod: dict) -> None:
        p = Pod(pod)
        with self._alloc_lock:
            for cid, owner in list(self._assignment.items()):
                if owner == (p.namespace, p.name):
                    self._assignment[cid] = None
            self.kubelet.claims = [
                c for c in self.kubelet.claims
                if not (c[0] == p.name and c[1] == p.namespace)]

    # --- convenience ---

    def free_chip_count(self) -> int:
        with self._alloc_lock:
            return sum(1 for o in self._assignment.values() if o is None)

    def add_target_pod(self, name: str, namespace: str = "default",
                       uid: str | None = None) -> Pod:
        """A running workload pod (no TPU request) to hot-mount into."""
        manifest = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": namespace,
                         **({"uid": uid} if uid else {})},
            "spec": {"containers": [{"name": "main", "image": "app"}]},
        }
        created = self.kube.create_pod(namespace, manifest)
        # containerStatuses so resolve_target has container IDs
        self.kube.set_pod_status(namespace, name, containerStatuses=[{
            "name": "main",
            "containerID": f"containerd://{name}-cid",
            "state": {"running": {}},
        }])
        deadline = 5.0
        pod = self.kube.wait_for_pod(
            namespace, name,
            lambda pj: pj is not None and Pod(pj).phase == "Running",
            timeout_s=deadline)
        assert pod is not None, f"target pod {name} did not reach Running"
        return Pod(self.kube.get_pod(namespace, name))

    def start(self) -> "FakeCluster":
        self.kubelet.start()
        return self

    def stop(self) -> None:
        self.kubelet.stop()
