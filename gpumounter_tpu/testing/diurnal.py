"""Diurnal fleet simulation for the autoscale capstone bench.

This module is the deterministic world that ``bench_diurnal.py`` runs
the REAL :class:`~gpumounter_tpu.autoscale.AutoscaleController` (and
the real :class:`ThroughputModel` inside it) against. Nothing here
reimplements a decision: the sim only plays the parts of the cluster
the controller consumes through its injected seams —

  fleet      ``DiurnalSim.payload(max_age_s=...)`` returns the same
             node-map shape FleetCollector produces: per-node
             ``capacity`` sections (free/held/warm/fenced over the
             8-chip 2x4 ICI board) plus per-tenant ``tenants``
             telemetry snapshots (cumulative steps/tokens counters,
             the shape jaxside/telemetry.py publishes). The fleet
             collector's ``refresh_if_stale`` uses the wall clock, so
             the bench drives the controller with this object and an
             injected simulated clock instead of a real FleetCollector.

  tenants    each tenant's serving stack follows a fixed
             Michaelis-Menten curve rate(b) = r_max*b/(b+b_half). The
             sim publishes batch sizes derived from true load
             (b = b_half*u/(1-u), so points lie exactly on the curve
             modulo batch jitter) — the model must REDISCOVER the
             curve from cumulative counters; the sim never hands it
             the answer.

  demand     per-tenant diurnal arrival curves (base + positive-half
             sine, phase-shifted per profile) with multiplicative
             noise; arrivals are precomputed once per seed so the
             autoscaled leg and both static control legs serve the
             exact same request sequence.

  elastic    a store/enqueue fake records every intent the controller
             writes; ``reconcile()`` then places/releases chips like
             the elastic reconciler + allocator would: grows claim
             warm chips first (the warm pool), then a contiguous ICI
             block on one healthy host, never a quarantined or dead
             host; shrinks release chips into the warm pool (the
             graceful-drain abstraction — drained chips stay
             reattachable until the TTL expires).

  chaos      ``kill_nodes`` drops hosts and their chips mid-run,
             ``quarantine_hosts`` feeds the health seam's
             excluded_hosts, ``fragment_wave`` simulates external
             churn shattering every free ICI block into singletons
             (the admissible-after-defrag trigger), and the defrag
             fake's ``run`` compacts hosts the way the real
             defragmenter's checkpoint-assisted migrations do.

Everything is seeded and wall-clock-free: identical seeds give
identical artifacts. See bench_diurnal.py for the gates.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field

from gpumounter_tpu.elastic.intents import Intent

#: chips per simulated host (the 2x4 ICI board capacity.py models)
CHIPS_PER_NODE = 8
#: ICI neighbors of chip i on the 2x4 board: {i^1, i-2, i+2}
_NEIGHBORS = {
    i: {n for n in (i ^ 1, i - 2, i + 2) if 0 <= n < CHIPS_PER_NODE}
    for i in range(CHIPS_PER_NODE)
}
#: steps each tenant reports per tick (cumulative-counter granularity)
STEPS_PER_TICK = 24


def _components(free: set[int]) -> list[set[int]]:
    """Connected components of a free-chip set under the ICI edges."""
    seen: set[int] = set()
    out: list[set[int]] = []
    for start in sorted(free):
        if start in seen:
            continue
        comp = {start}
        queue = [start]
        while queue:
            chip = queue.pop()
            for nbr in _NEIGHBORS[chip]:
                if nbr in free and nbr not in comp:
                    comp.add(nbr)
                    queue.append(nbr)
        seen |= comp
        out.append(comp)
    return out


@dataclass
class TenantProfile:
    """One tenant's demand curve + serving characteristics."""

    name: str          # namespace/pod
    base_rps: float    # floor demand, requests/sec
    amp_rps: float     # diurnal amplitude (positive-half sine)
    phase: float       # fraction of a day the peak is shifted by
    min_chips: int = 2
    r_max: float = 5000.0   # tokens/sec plateau of the true MM curve
    b_half: float = 12.0    # tokens/step at half saturation

    def rate(self, tick: int, day_ticks: int) -> float:
        wave = math.sin(2.0 * math.pi
                        * (tick / float(day_ticks) + self.phase))
        return self.base_rps + self.amp_rps * max(0.0, wave)

    def peak_rps(self, day_ticks: int) -> float:
        return max(self.rate(t, day_ticks) for t in range(day_ticks))

    def mean_rps(self, day_ticks: int) -> float:
        return sum(self.rate(t, day_ticks)
                   for t in range(day_ticks)) / float(day_ticks)


def build_arrivals(profiles: list[TenantProfile], ticks: int,
                   day_ticks: int, tick_s: float,
                   seed: int) -> dict[str, list[float]]:
    """Requests arriving per tick per tenant — computed ONCE per seed
    so every leg (autoscaled, static-peak, static-mean) serves the
    identical sequence."""
    rng = random.Random(seed)
    out: dict[str, list[float]] = {}
    for profile in profiles:
        series = []
        for tick in range(ticks):
            jitter = max(0.0, rng.gauss(1.0, 0.05))
            series.append(profile.rate(tick, day_ticks) * tick_s * jitter)
        out[profile.name] = series
    return out


@dataclass
class _Tenant:
    profile: TenantProfile
    chips: set = field(default_factory=set)    # {(node, chip_idx)}
    queue: float = 0.0
    steps: float = 0.0
    tokens: float = 0.0
    requests: float = 0.0
    served_work: float = 0.0
    cap_work: float = 0.0
    breach_ticks: list = field(default_factory=list)
    snapshot: dict | None = None


class _Node:
    __slots__ = ("held", "warm", "killed")

    def __init__(self):
        self.held: dict[int, str] = {}       # chip -> owner
        self.warm: dict[int, int] = {}       # chip -> expiry tick
        self.killed = False

    def free_set(self) -> set[int]:
        return (set(range(CHIPS_PER_NODE)) - set(self.held)
                - set(self.warm))


class _Store:
    """Elastic intent store seam (the controller's durable output)."""

    def __init__(self):
        self.intents: dict[tuple[str, str], Intent] = {}
        self.puts: list[tuple[str, str, Intent]] = []

    def put(self, namespace: str, pod_name: str,
            intent: Intent) -> Intent:
        self.intents[(namespace, pod_name)] = intent
        self.puts.append((namespace, pod_name, intent))
        return intent

    def list(self):
        return [(ns, pod, i)
                for (ns, pod), i in sorted(self.intents.items())]


class _Elastic:
    def __init__(self, store: _Store):
        self.store = store
        self.enqueued: list[tuple[str, str]] = []

    def enqueue(self, namespace: str, pod_name: str) -> None:
        self.enqueued.append((namespace, pod_name))


class _Api:
    """ApiHealth seam; the bench flips ``down`` for the outage window."""

    def __init__(self):
        self.down = False

    def ok(self) -> bool:
        return not self.down

    def state(self) -> str:
        return "down" if self.down else "healthy"


class _Slo:
    """SLO seam; the bench flips ``burning`` for the burn window."""

    def __init__(self):
        self.burning = False

    def evaluate(self) -> dict:
        objectives = [{"name": "tenant-disruption-free-minutes",
                       "breached": False,
                       "burn_fast": 3.5 if self.burning else 0.1}]
        return {"burn_threshold": 2.0, "objectives": objectives}


class _Health:
    def __init__(self):
        self.quarantined: set[str] = set()

    def excluded_hosts(self) -> frozenset:
        return frozenset(self.quarantined)


class _Defrag:
    """DefragController seam: plan() advertises the compactable hosts,
    run() performs the compaction (the sim's stand-in for the real
    checkpoint-assisted migrations)."""

    def __init__(self, sim: "DiurnalSim"):
        self.sim = sim
        self.requests = 0
        self.runs = 0

    def plan(self) -> dict:
        self.requests += 1
        moves = [{"node": name} for name, node in self.sim.nodes.items()
                 if not node.killed and len(_components(
                     node.free_set())) > 1]
        return {"id": f"dfp-sim-{self.requests}", "moves": moves}

    def run(self, plan_id: str | None = None) -> dict:
        self.runs += 1
        moved = self.sim.compact()
        return {"id": plan_id, "status": "completed", "moved": moved}


class DiurnalSim:
    """The simulated fleet + tenant world (see module docstring)."""

    def __init__(self, profiles: list[TenantProfile], n_nodes: int,
                 seed: int, tick_s: float = 60.0,
                 per_chip_rps: float = 1.0, day_ticks: int = 1440,
                 warm_ttl_ticks: int = 240, slo_wait_s: float = 180.0,
                 util_cap: float = 0.97):
        self.rng = random.Random(seed + 1)
        self.tick_s = tick_s
        self.per_chip_rps = per_chip_rps
        self.day_ticks = day_ticks
        self.warm_ttl_ticks = warm_ttl_ticks
        self.slo_wait_s = slo_wait_s
        self.util_cap = util_cap
        self.now = 1_000_000.0
        self.tick_index = 0
        self.nodes: dict[str, _Node] = {
            f"sim-{i:04d}": _Node() for i in range(n_nodes)}
        self.tenants: dict[str, _Tenant] = {
            p.name: _Tenant(profile=p) for p in profiles}
        # seams the controller is wired to
        self.store = _Store()
        self.elastic = _Elastic(self.store)
        self.api = _Api()
        self.slo = _Slo()
        self.health = _Health()
        self.defrag = _Defrag(self)
        # counters the bench gates on
        self.warm_attaches = 0
        self.scatter_allocs = 0
        self.unplaced = 0
        self.quarantine_placements = 0
        self.compaction_moves = 0
        self.ballast_surge = 0
        # seed intents at the initial provision
        for p in profiles:
            desired = max(p.min_chips,
                          int(math.ceil(p.rate(0, day_ticks)
                                        / per_chip_rps)))
            ns, pod = p.name.split("/", 1)
            self.store.put(ns, pod, Intent(desired_chips=desired,
                                           min_chips=p.min_chips))

    def controller_kwargs(self) -> dict:
        """Everything AutoscaleController needs, wired to this sim."""
        return {"elastic": self.elastic, "capacity": None,
                "fleet": self, "slo": self.slo, "apihealth": self.api,
                "health": self.health, "defrag": self.defrag,
                "clock": lambda: self.now}

    # --- fleet seam -----------------------------------------------------

    def payload(self, max_age_s: float | None = None) -> dict:  # noqa: ARG002
        nodes: dict[str, dict] = {}
        alive = [n for n, node in sorted(self.nodes.items())
                 if not node.killed]
        for name in alive:
            node = self.nodes[name]
            nodes[name] = {"capacity": {
                "total": CHIPS_PER_NODE,
                "free": sorted(node.free_set()),
                "held": dict(node.held),
                "warm": sorted(node.warm),
                "fenced": [],
            }}
        # tenant telemetry rides the rollup from whichever worker
        # published it; merge_tenants dedups by name, so one section on
        # the first alive host is equivalent to per-home-node publishes
        if alive:
            nodes[alive[0]]["tenants"] = {
                name: dict(t.snapshot)
                for name, t in self.tenants.items()
                if t.snapshot is not None}
        return {"at": self.now, "nodes": nodes}

    # --- ballast (the rest of the fleet's workloads) --------------------

    def seed_ballast(self, open_nodes: int) -> None:
        """All hosts beyond the first ``open_nodes`` are occupied by
        non-autoscaled workloads, each left with only the {0, 3}
        non-adjacent free pair — they count toward after-defrag
        capacity but never offer a 2-block."""
        for i, (name, node) in enumerate(sorted(self.nodes.items())):
            if i < open_nodes:
                continue
            for chip in range(CHIPS_PER_NODE):
                if chip not in (0, 3):
                    node.held[chip] = f"ballast/b{i:04d}"

    def fragment_wave(self) -> int:
        """External churn shatters the fleet: ballast pods land until
        no free ICI block of 2+ chips survives anywhere. Returns the
        number of chips the surge claimed."""
        claimed = 0
        for name, node in self.nodes.items():
            if node.killed:
                continue
            free = node.free_set()
            while True:
                comps = [c for c in _components(free) if len(c) >= 2]
                if not comps:
                    break
                comps.sort(key=len, reverse=True)
                victim = sorted(comps[0])[len(comps[0]) // 2]
                node.held[victim] = "ballast/surge"
                free.discard(victim)
                claimed += 1
        self.ballast_surge += claimed
        return claimed

    def compact(self) -> int:
        """Defrag execution: repack every live host's held chips to the
        low indices (the migration-backed compaction), leaving free +
        warm chips as one contiguous tail. Returns chips relocated."""
        moved = 0
        for node_name, node in sorted(self.nodes.items()):
            if node.killed:
                continue
            old_sorted = sorted(node.held)
            remap = {old_idx: new_idx
                     for new_idx, old_idx in enumerate(old_sorted)
                     if new_idx != old_idx}
            if not remap and not node.warm:
                continue
            moved += len(remap)
            node.held = {remap.get(c, c): node.held[c]
                         for c in old_sorted}
            node.warm = {len(old_sorted) + i: exp
                         for i, (_, exp) in enumerate(
                             sorted(node.warm.items()))}
            # fix tenant chip bookkeeping for relocated chips
            if remap:
                for tenant in self.tenants.values():
                    tenant.chips = {
                        (n, remap[c]) if n == node_name and c in remap
                        else (n, c)
                        for (n, c) in tenant.chips}
        self.compaction_moves += moved
        return moved

    # --- chaos ----------------------------------------------------------

    def kill_nodes(self, count: int) -> list[str]:
        """Hard-kill hosts that currently hold tenant chips: the chips
        are gone, the host leaves the fleet payload entirely."""
        tenant_hosts = sorted({n for t in self.tenants.values()
                               for (n, _) in t.chips})
        victims = self.rng.sample(tenant_hosts,
                                  min(count, len(tenant_hosts)))
        for name in victims:
            self.nodes[name].killed = True
            self.nodes[name].warm.clear()
            for tenant in self.tenants.values():
                tenant.chips = {(n, c) for (n, c) in tenant.chips
                                if n != name}
        return victims

    def quarantine_hosts(self, count: int) -> list[str]:
        alive = [n for n, node in sorted(self.nodes.items())
                 if not node.killed]
        picked = self.rng.sample(alive, min(count, len(alive)))
        self.health.quarantined.update(picked)
        return picked

    def release_quarantine(self) -> None:
        self.health.quarantined.clear()

    # --- the elastic reconciler + allocator abstraction -----------------

    def reconcile(self) -> None:
        """Drive every tenant's placed chips toward its intent."""
        for (ns, pod), intent in sorted(self.store.intents.items()):
            tenant = self.tenants.get(f"{ns}/{pod}")
            if tenant is None:
                continue
            current = len(tenant.chips)
            if intent.desired_chips > current:
                self._allocate(tenant, intent.desired_chips - current)
            elif intent.desired_chips < current:
                self._release(tenant, current - intent.desired_chips)

    def _eligible(self) -> list[tuple[str, _Node]]:
        out = []
        for name, node in sorted(self.nodes.items()):
            if node.killed:
                continue
            if name in self.health.quarantined:
                # counted, never used: the bench gates this at zero
                continue
            out.append((name, node))
        return out

    def _allocate(self, tenant: _Tenant, need: int) -> None:
        owner = tenant.profile.name
        # 1. warm pool first: reclaimable drained chips attach fastest
        for name, node in self._eligible():
            while need and node.warm:
                chip = min(node.warm)
                del node.warm[chip]
                node.held[chip] = owner
                tenant.chips.add((name, chip))
                self.warm_attaches += 1
                need -= 1
        if not need:
            return
        # 2. one contiguous ICI block on a single healthy host
        best: tuple[str, _Node, set] | None = None
        for name, node in self._eligible():
            for comp in _components(node.free_set()):
                if len(comp) >= need and (
                        best is None or len(comp) < len(best[2])):
                    best = (name, node, comp)
        if best is not None:
            name, node, comp = best
            for chip in sorted(comp)[:need]:
                node.held[chip] = owner
                tenant.chips.add((name, chip))
            return
        # 3. scatter fallback (counted; the controller's feasibility
        # gate should make this rare)
        for name, node in self._eligible():
            for chip in sorted(node.free_set()):
                if not need:
                    return
                node.held[chip] = owner
                tenant.chips.add((name, chip))
                self.scatter_allocs += 1
                need -= 1
        self.unplaced += need

    def _release(self, tenant: _Tenant, count: int) -> None:
        """Graceful drain: released chips enter the warm pool and stay
        reattachable until the TTL expires."""
        victims = sorted(tenant.chips)[-count:]
        expiry = self.tick_index + self.warm_ttl_ticks
        for (name, chip) in victims:
            tenant.chips.discard((name, chip))
            node = self.nodes[name]
            node.held.pop(chip, None)
            if not node.killed:
                node.warm[chip] = expiry

    # --- time -----------------------------------------------------------

    def tick(self, arrivals: dict[str, list[float]]) -> None:
        """Advance one tick: expire warm chips, serve demand, publish
        telemetry."""
        i = self.tick_index
        self.now += self.tick_s
        for node in self.nodes.values():
            if node.killed:
                continue
            for chip in [c for c, exp in node.warm.items() if exp <= i]:
                del node.warm[chip]
        for name, tenant in self.tenants.items():
            arr = arrivals[name][i]
            chips = len(tenant.chips)
            cap = chips * self.per_chip_rps * self.tick_s
            demand = arr + tenant.queue
            served = min(cap, demand)
            tenant.queue = demand - served
            tenant.requests += arr
            tenant.served_work += served
            tenant.cap_work += cap
            wait_s = (tenant.queue / (chips * self.per_chip_rps)
                      if chips else float("inf"))
            if wait_s > self.slo_wait_s:
                tenant.breach_ticks.append(i)
            # telemetry: on-curve batch/rate derived from true load
            load = (demand / cap) if cap > 0 else self.util_cap
            u = min(0.95, min(self.util_cap, load))
            batch = tenant.profile.b_half * u / (1.0 - u)
            batch *= 1.0 + self.rng.uniform(-0.08, 0.08)
            rate = (tenant.profile.r_max * batch
                    / (batch + tenant.profile.b_half))
            tenant.steps += STEPS_PER_TICK
            tenant.tokens += batch * STEPS_PER_TICK
            tenant.snapshot = {
                "steps": {"count": tenant.steps},
                "tokens_total": round(tenant.tokens, 3),
                "tokens_per_s": round(rate, 3),
                "queue_depth": round(tenant.queue, 1),
                "at": self.now,
            }
        self.tick_index += 1

    # --- leg summary ----------------------------------------------------

    def utilization(self) -> float:
        cap = sum(t.cap_work for t in self.tenants.values())
        served = sum(t.served_work for t in self.tenants.values())
        return (served / cap) if cap else 0.0

    def total_requests(self) -> float:
        return sum(t.requests for t in self.tenants.values())

    def breach_ticks(self) -> dict[str, list[int]]:
        return {name: list(t.breach_ticks)
                for name, t in sorted(self.tenants.items())
                if t.breach_ticks}


def run_static_leg(profiles: list[TenantProfile],
                   arrivals: dict[str, list[float]],
                   chips_by_tenant: dict[str, int], ticks: int,
                   tick_s: float, per_chip_rps: float,
                   slo_wait_s: float) -> dict:
    """The control leg: the same arrival sequence served by a FIXED
    per-tenant allocation (no controller, no chaos). Returns the same
    utilization/breach summary shape as the autoscaled leg."""
    served_total = cap_total = 0.0
    breach_ticks = 0
    queues = {p.name: 0.0 for p in profiles}
    for i in range(ticks):
        for p in profiles:
            chips = chips_by_tenant[p.name]
            cap = chips * per_chip_rps * tick_s
            demand = arrivals[p.name][i] + queues[p.name]
            served = min(cap, demand)
            queues[p.name] = demand - served
            served_total += served
            cap_total += cap
            wait_s = (queues[p.name] / (chips * per_chip_rps)
                      if chips else float("inf"))
            if wait_s > slo_wait_s:
                breach_ticks += 1
    return {
        "chips_total": sum(chips_by_tenant.values()),
        "utilization": round(served_total / cap_total, 4)
        if cap_total else 0.0,
        "breach_ticks_total": breach_ticks,
    }
