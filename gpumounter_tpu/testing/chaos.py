"""Seeded crash-consistency chaos harness over the fake cluster.

Runs the real control plane — MasterApp + slice coordinator + elastic
reconciler + migration orchestrator over real loopback gRPC workers on a
multi-node FakeCluster — under randomized-but-reproducible failpoint
schedules (gpumounter_tpu/faults), then asserts the global safety
invariants after convergence:

  1. no chip held by two pods (no double-mounted /dev/accel* node),
  2. no ownerless grant (every injected node is backed by a scheduler
     booking — a node without one is a leaked mount),
  3. accounting parity (every booked chip is actually mounted: slave-pod
     books match injected nodes),
  4. every migration journal is terminal: outcome succeeded / rolled-back
     / aborted with phase=done — never stranded, never half-rolled-back,
  5. observability closure (gpumounter_tpu/obs): no orphan open spans —
     every span entered was exited, even through injected crashes,
  6. every operation leaves a terminal audit record: each terminal
     migration journal has a matching audit record, and every audit
     record carries an outcome and a trace id (a crashed-and-resumed
     operation must not vanish from the trail),
  7. no leaked channels: the shared ChannelPool's books stay exact —
     dialed == live + closed, and the live set never exceeds the
     worker count (a WorkerClient that closed a pooled channel, or a
     pool that lost one, breaks the identity),
  8. fleet rollups never double-count a node across collector restarts:
     two freshly-constructed FleetCollectors (a "restart") rolling up
     the converged cluster agree exactly — same node set (every worker
     once), same per-node mount counts, and the fleet total is the sum
     of the per-node counts in both,
  9. single shard owner per node (run_shard_scenario): across seeded
     master crashes, restarts, and lease takeovers, no shard — and
     therefore no node, since the hash ring maps each node to exactly
     one shard — is ever claimed by two replica views at once, and the
     fleet converges back to every shard owned,
 10. ledger agreement (run_worker_crash_scenario): after a worker
     crash at ANY seeded failpoint followed by restart + ledger replay
     (worker/resync.py), books == mounts == ledger — no open
     transactions survive, and the ledger's net holdings equal both
     the injected nodes and the scheduler's bookings for every pod on
     the node,
 11. evacuation re-convergence (run_node_kill_scenario): a killed node
     (server dead, worker pod gone, Node NotReady) is confirmed and
     evacuated by the recovery controller — its pool bookings
     released — and every elastic intent stranded on it re-converges
     on a healthy node once its pod is rescheduled,
 12. fencing (run_fencing_scenario): no stale-epoch write is ever
     applied — a partitioned old shard owner's mutations are rejected
     FENCED and provably change nothing, while the new owner's traffic
     flows,
 13. tenant disruption closure (armed by attach_tenant): after a
     terminal migration/heal/evacuation, no fake tenant's disruption
     window is left open, every signalled-cause window carries the
     control-plane trace id the signal delivered, and that trace id
     resolves in the trace ring — tenant-perceived downtime is never
     unattributable,
 14. API-outage degraded mode (run_api_outage_scenario): with the fake
     API server partitioned mid-mount/-migrate/-heal/-recovery, no
     destructive mutation lands from stale reads (reconciles park
     read-only, the migration machine holds at a journaled phase
     boundary, evacuations are suspended), no booking leaks (slave
     releases defer into the ledger retry queue), and after the heal
     every deferred annotation write lands exactly once — newest value
     wins, CAS losers dropped — and books == mounts == ledger ==
     intents; the negative control (replay disabled) must be DETECTED
     as divergence,
 15. lock-order consistency (utils/locks.py): every nested lock
     acquisition observed at runtime across the instrumented modules
     (metrics instruments, the fake apiserver, the migration machine,
     the tracer, the worker ledger) forms an acyclic order — and, via
     the TPM_LOCK_TRACE export cross-checked by `python -m
     tools.tpulint --verify-dynamic`, never contradicts the static
     nesting graph tpulint extracted from the source,
 16. trace-assembly closure (obs/assembly.py): every CLEAN mount/
     remove operation the harness drove (no fault armed, completed
     successfully) assembles completely from the span stores — no
     orphan spans whose parent never arrived, no successful rpc.* span
     missing its worker-side half — and the assembled critical path's
     per-phase attribution sums to the edge span's wall time (within
     rounding), so "where did the latency go" is answerable for every
     benched operation. The negative control (worker spans dropped
     from the ring) must be DETECTED as incomplete assembly.
 17. capacity-plane agreement (obs/capacity.py): after every scenario,
     the /capacity payload's per-node free/held/warm/fenced chips
     exactly equal the fake scheduler's ground truth — books ==
     mounts == ledger == capacity — so the pane controllers will act
     on (the defragmenter, the autoscaler) can never drift from
     reality undetected. The negative control (withhold_unmount: one
     held chip's kubelet claim silently erased, as a lost unmount
     would) must be DETECTED as divergence.
 18. defrag closure (run_defrag_scenario): the fleet fragmentation
     index sampled at the plan's barrier points is monotonically
     non-increasing, every executed move succeeded with a terminal
     journal, and every move's tenant disruption is trace-attributed
     (assembled migrate-phase wall time),
 19. fractional-share agreement (run_share_scenario): after every
     scenario the three share ledgers agree chip-for-chip and
     value-for-value — master share books == policy-map entries (the
     userspace engine standing in for the kernel map on fake
     backends) == worker ledger share records — and a metered tenant
     driven past its token budget is throttled identically by the
     userspace engine and by the interpreter executing the real
     in-kernel program bytecode. The negative control
     (disable_enforcement: the engine flipped to pure bookkeeping)
     must be DETECTED as decision divergence.

 20. gray-failure attribution closure (run_gray_scenario): every
     automatic quarantine the health plane committed is trace-attributed
     in the flight recorder to at least one concrete scoring signal
     (mount_p95_outlier / mount_error_ratio / canary_failures /
     breaker_open — never a shrug), no node outside the deliberately
     degraded set is ever quarantined (zero false positives: a healthy
     fleet driven through the same scenario must end with an empty
     quarantine set), and every deliberately degraded node IS
     quarantined by the end — which makes the negative control
     (disable_scorer: the plane switched off while the node limps)
     DETECTED as a missed detection,

 21. autoscale decision closure (run_autoscale_scenario): after the
     autoscaler has grown and shrunk tenants mid-chaos and the fleet
     converged, every tenant's mounted chips equal its declared
     intent (intents == books == mounts == ledger — the books/mounts/
     ledger legs are invariants 1-3, 10 and 17 over the same run);
     every fired grow/shrink decision is trace-attributed and carries
     a matching `autoscale.decision` audit record; and NO decision
     ever fired through a recorded-closed gate (paused, degraded API,
     or a burning tenant SLO). The negative control (disable_gates:
     enforcement off while the controller is paused) must be DETECTED
     as gate bypass,

Determinism: all randomness flows from one seed (`random.Random(seed)`);
the executed schedule is logged step by step and embedded in the
InvariantViolation message so a failing run reproduces from its seed.
Fault schedules are count-limited one-shots armed immediately before
each operation and cleared right after it, so no fault leaks into the
convergence phase — convergence is exactly what a healed production
cluster would do (reconciler passes + resume_interrupted re-drives).
"""

from __future__ import annotations

import os
import random
import time

from gpumounter_tpu.collector.collector import TpuCollector
from gpumounter_tpu.collector.podresources import PodResourcesClient
from gpumounter_tpu.faults import failpoints
from gpumounter_tpu.k8s.client import NotFoundError
from gpumounter_tpu.k8s.types import Pod
from gpumounter_tpu.master.app import MasterApp, WorkerRegistry
from gpumounter_tpu.obs import trace
from gpumounter_tpu.obs.audit import AUDIT
from gpumounter_tpu.rpc.client import ChannelPool, WorkerClient
from gpumounter_tpu.testing.cluster import FakeCluster
from gpumounter_tpu.utils import locks
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.worker.mounter import MountTarget, TpuMounter
from gpumounter_tpu.worker.server import TpuMountService, build_server

logger = get_logger("testing.chaos")

NODE_A, NODE_B = "chaos-a", "chaos-b"


class InvariantViolation(AssertionError):
    """A global safety invariant failed to hold after convergence."""


class TenantSim:
    """A fake tenant process over the fake cluster: a paced step loop
    plus the REAL jaxside watchers (watch_migration /
    watch_chip_replacements / watch_disruptions) driving the REAL
    TenantTelemetry SDK — so the harness and bench measure the exact
    code a tenant would run.

    The step loop pauses on the quiesce signal (state packed) and
    resumes on the resume signal (state restored), so tenant-visible
    migration downtime is a genuinely measured gap, not a simulation
    constant. `extra_pods` lets the sim watch a migration destination
    pod too — the tenant process logically spans both ends of a move.
    """

    def __init__(self, kube, namespace: str, pod: str,
                 extra_pods: tuple = (), step_s: float = 0.004,
                 publish_url: str | None = None,
                 token: str | None = None):
        import threading

        from gpumounter_tpu.jaxside.telemetry import TenantTelemetry
        self.kube = kube
        self.namespace = namespace
        self.pod = pod
        self.telemetry = TenantTelemetry(
            tenant=f"{namespace}/{pod}", namespace=namespace, pod=pod,
            publish_url=publish_url, token=token,
            # test-speed knobs: stalls detected at half a second, minute
            # accounting rolls every 2 s so short runs still count them
            stall_min_s=0.5, minute_s=2.0)
        self._step_s = step_s
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._threads: list[threading.Thread] = []
        watched = [(namespace, pod)] + [tuple(p) for p in extra_pods]

        def _stepper() -> None:
            while not self._stop.is_set():
                if self._pause.is_set():
                    self._stop.wait(0.002)
                    continue
                with self.telemetry.step(tokens=256.0, queue_depth=1.0):
                    self._stop.wait(self._step_s)

        def _on_quiesce(signal: dict) -> None:
            self._pause.set()      # HotResumable.pack stand-in
            time.sleep(0.005)

        def _on_checkpoint(signal: dict) -> None:
            time.sleep(0.002)      # durable host-side save stand-in

        def _on_resume(signal: dict) -> None:
            if signal.get("checkpointed"):
                time.sleep(0.005)  # warm restore: copy packed host buffers
            else:
                time.sleep(0.08)   # cold restore: rebuild + re-shard state
            self._pause.clear()

        def _on_heal(marker: dict) -> None:
            self._pause.set()      # repack + restore blocks the loop
            time.sleep(0.005)
            self._pause.clear()

        def _spawn(target, *args, **kwargs) -> None:
            thread = threading.Thread(target=target, args=args,
                                      kwargs=kwargs, daemon=True)
            self._threads.append(thread)
            thread.start()

        _spawn(_stepper)
        from gpumounter_tpu.jaxside.heal import watch_chip_replacements
        from gpumounter_tpu.jaxside.migrate import watch_migration
        from gpumounter_tpu.jaxside.telemetry import watch_disruptions
        for ns, name in watched:
            _spawn(watch_migration, kube, ns, name,
                   self.telemetry.migration_quiesce(_on_quiesce),
                   on_resume=self.telemetry.migration_resume(_on_resume),
                   stop=self._stop, watch_timeout_s=1.0,
                   on_checkpoint=_on_checkpoint)
            _spawn(watch_chip_replacements, kube, ns, name,
                   self.telemetry.heal(_on_heal), stop=self._stop,
                   watch_timeout_s=1.0)
            _spawn(watch_disruptions, kube, ns, name,
                   self.telemetry.external_disruption, stop=self._stop,
                   watch_timeout_s=1.0)

    def settle(self, timeout_s: float = 5.0) -> None:
        """Wait until no disruption window is open (the step loop
        auto-closes them) or the deadline passes."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.telemetry.snapshot()["disruption"]["open"]:
                return
            time.sleep(0.02)

    def stop(self) -> None:
        self._stop.set()
        self._pause.clear()
        for thread in self._threads:
            thread.join(timeout=3.0)


#: (failpoint name, action) pools the scenarios draw from. Everything is
#: count-limited so an armed-but-unfired fault cannot outlive its op
#: (the harness also disarms after every op as a belt-and-braces).
FAULTS_COMMON = [
    ("rpc.client.call", "1*unavailable(chaos drop)"),
    ("rpc.client.call", "1*delay(0.05)"),
    ("worker.rpc", "1*delay(0.05)"),
    ("worker.mount.mknod", "1*error(chaos mknod)"),
    ("worker.mount.mknod", "1*pass->1*error(chaos mknod 2nd)"),
    ("worker.mount.before_grant", "1*crash(chaos)"),
    ("worker.mount.after_grant", "1*crash(chaos)"),
    ("worker.unmount.before_revoke", "1*error(chaos revoke)"),
    ("k8s.patch_pod.status", "1*return(409)"),
    ("k8s.patch_pod.status", "1*return(500)"),
]
FAULTS_ELASTIC = FAULTS_COMMON + [
    ("elastic.reconcile", "1*crash(chaos)"),
    ("elastic.before_grow", "1*crash(chaos)"),
]
FAULTS_MIGRATE = FAULTS_COMMON + [
    ("migrate.phase.quiesce", "1*crash(chaos)"),
    ("migrate.phase.checkpoint", "1*crash(chaos)"),
    ("migrate.phase.drain", "1*crash(chaos)"),
    ("migrate.phase.remount", "1*crash(chaos)"),
    ("migrate.phase.resume", "1*crash(chaos)"),
    ("migrate.phase.verify", "1*crash(chaos)"),
    ("migrate.persist", "1*error(chaos persist)"),
]


class ChaosHarness:
    """One fake two-node cluster + live control plane per scenario run."""

    def __init__(self, root: str, seed: int,
                 nodes: dict[str, int] | None = None):
        self.root = root
        self.seed = seed
        self.rng = random.Random(seed)
        self.schedule: list[str] = []
        self.cluster = FakeCluster(
            root, nodes=nodes or {NODE_A: 6, NODE_B: 6})
        self.cfg = self.cluster.cfg.replace(
            migrate_quiesce_timeout_s=0.3,
            migrate_checkpoint_timeout_s=0.3,
            migrate_resume_timeout_s=0.3,
            migrate_poll_interval_s=0.02,
            elastic_resync_interval_s=30.0,
            elastic_backoff_base_s=0.05,
            elastic_backoff_cap_s=0.2,
            elastic_min_reconcile_interval_s=0.0,
            rpc_probe_timeout_s=5.0,
            rpc_quiesce_timeout_s=5.0,
            rpc_retry_base_s=0.02,
            rpc_retry_cap_s=0.1,
            k8s_write_retry_base_s=0.02,
            # Recovery plane: fast confirmation so the node-kill
            # scenario's detect->evacuate loop runs in test time.
            recovery_confirm_failures=2,
            recovery_grace_s=0.0,
            recovery_probe_timeout_s=2.0,
            # API-outage degraded mode at test speed: degraded after 2
            # outage-shaped failures, down after 50 ms of continuous
            # failure, recovered after the default 2-success hysteresis;
            # deferred writes go to a durable queue under the harness
            # root (invariant 14 re-reads it across the heal).
            api_health_degraded_failures=2,
            api_health_down_after_s=0.05,
            api_health_recovery_successes=2,
            writebehind_dir=os.path.join(root, "writebehind"),
            # High threshold: chaos injects isolated transport faults by
            # design; the breaker's own behavior has dedicated tests.
            breaker_failure_threshold=50,
            # Health plane OFF by default: the harness runs every fake
            # node in ONE process, so the global metrics registry folds
            # all nodes' mount stats together — fleet-wide error ratios
            # from injected faults would read as per-node signals and
            # quarantine an innocent node mid-scenario.
            # run_gray_scenario re-enables it with per-node entries the
            # harness measures itself (see _gray_entries).
            health_enabled=False)
        self.services: dict[str, TpuMountService] = {}
        self._servers: dict[str, object] = {}   # node -> live gRPC server
        self._ip_by_node: dict[str, str] = {}
        self._port_by_ip: dict[str, int] = {}
        #: nodes killed via kill_node (skipped by converge/invariants)
        self.dead_nodes: set[str] = set()
        #: run_worker_crash_scenario arms this so check_invariants also
        #: asserts invariant 10 (ledger agreement) — the base scenarios
        #: crash workers WITHOUT restarting them, so their ledgers
        #: legitimately hold open txns at check time.
        self.check_ledgers = False
        #: clean (fault-free, completed) mount/remove operations, each
        #: run under a chaos.<op> root span — invariant 16 asserts
        #: every one assembles completely with an exact critical path.
        self.traced_ops: list[dict] = []
        # Pooled channels, like the production master: the harness's
        # invariant 7 asserts the pool's books stay exact under chaos
        # (every dialed channel either live in the cache or closed).
        self.channel_pool = ChannelPool(cfg=self.cfg)
        #: (namespace, pod) -> node, for every target pod we created
        self.pods: dict[tuple[str, str], str] = {}
        #: (namespace, pod) -> TenantSim: fake tenants running the real
        #: jaxside telemetry SDK; non-empty arms invariant 13.
        self.tenant_sims: dict[tuple[str, str], TenantSim] = {}
        #: terminal defrag run views (run_defrag_scenario appends);
        #: non-empty arms invariant 18.
        self.defrag_runs: list[dict] = []
        #: run_share_scenario arms this so check_invariants also
        #: asserts invariant 19 (fractional-share agreement + throttle
        #: decision parity).
        self.vchip_armed = False
        #: run_gray_scenario arms this so check_invariants also asserts
        #: invariant 20 (gray-failure attribution closure); gray_nodes
        #: is the set of nodes the scenario deliberately degraded.
        self.gray_armed = False
        self.gray_nodes: set[str] = set()
        #: run_autoscale_scenario arms this so check_invariants also
        #: asserts invariant 21 (autoscale decision closure); the pass
        #: records carry each decision's gates/trace for the audit.
        self.autoscale_armed = False
        self.autoscale_passes: list[dict] = []
        self.autoscale_pods: list[tuple[str, str]] = []
        #: run_watch_store_scenario arms this so check_invariants also
        #: asserts invariant 22 (watch-store index parity): after
        #: severed watches, 410 storms and a master restart the
        #: informer's indexes must agree exactly with a fresh
        #: list-backed view of the same cluster.
        self.watchstore_armed = False
        self.watch_store = None
        self._watch_cfg = None
        self._ws_serial = 0
        self._ws_default: list[str] = []
        self._ws_pool: dict[str, str] = {}
        self.app: MasterApp | None = None

    # --- lifecycle ---

    def _build_node_service(self, name: str) -> TpuMountService:
        """One node's worker stack: collector + mounter + durable
        ledger (per-node dir under the harness root — building a second
        service over the same dir IS the worker restart)."""
        node_cfg = self.cluster.node_cfg(name, self.cfg).replace(
            ledger_dir=os.path.join(self.root, f"ledger-{name}"))
        node = self.cluster.node(name)
        collector = TpuCollector(
            backend=node.backend,
            podresources=PodResourcesClient(node.kubelet_socket,
                                            timeout_s=5.0),
            cfg=node_cfg)
        mounter = TpuMounter(node.backend, cfg=node_cfg,
                             kube=self.cluster.kube)
        dev_base = os.path.join(self.root, f"container-dev-{name}")
        os.makedirs(dev_base, exist_ok=True)

        def _resolver(pod, _base=dev_base):
            d = os.path.join(_base, f"{pod.namespace}-{pod.name}")
            os.makedirs(d, exist_ok=True)
            return MountTarget(
                dev_dir=d, description=f"{pod.namespace}/{pod.name}",
                pod=pod)

        mounter.resolve_target = _resolver
        return TpuMountService(self.cluster.kube, collector=collector,
                               mounter=mounter, cfg=node_cfg)

    def _serve_node(self, name: str, service: TpuMountService) -> None:
        server = build_server(service, address="localhost:0")
        server.start()
        old = self._servers.get(name)
        self._servers[name] = server
        self.services[name] = service
        ip = self._ip_by_node[name]
        old_port = self._port_by_ip.get(ip)
        self._port_by_ip[ip] = server.bound_port
        if old is not None:
            # Production parity: a replaced worker's cached channel must
            # not serve one more RPC (WorkerRegistry does this on
            # address change; the harness maps ip->port itself).
            old.stop(grace=None)
            self.channel_pool.invalidate(f"localhost:{old_port}",
                                         "worker-restart")

    def start(self) -> "ChaosHarness":
        # Per-scenario observability baseline: the closure invariants
        # (open spans, audit records) must judge THIS run only.
        trace.TRACER.reset()
        AUDIT.reset()
        # Fresh flight recorder: invariant 20 audits THIS run's health
        # transitions only.
        from gpumounter_tpu.obs.flight import FLIGHT
        FLIGHT.reset()
        # Fresh per-endpoint ApiHealth machines: a previous scenario's
        # outage verdict must not park this run's subsystems (the
        # master, workers and store all share the process-global
        # instance, exactly like one real process would).
        from gpumounter_tpu.k8s import health as k8s_health
        k8s_health.reset_all()
        # Fresh policy-engine table with enforcement ON: a previous
        # run's share scopes must not leak into this run's invariant-19
        # books comparison, and the negative control
        # (disable_enforcement) must not outlive its scenario.
        from gpumounter_tpu.cgroup.policy import POLICY_ENGINE
        POLICY_ENGINE.reset()
        POLICY_ENGINE.enforce = True
        self.cluster.start()
        for i, name in enumerate(self.cluster.node_names):
            self._ip_by_node[name] = f"10.9.0.{i + 1}"
            self.cluster.kube.create_node(name, ready=True)
            self._serve_node(name, self._build_node_service(name))
            self.cluster.kube.create_pod(self.cfg.worker_namespace, {
                "metadata": {"name": f"chaos-worker-{name}",
                             "namespace": self.cfg.worker_namespace,
                             "labels": {"app": "tpu-mounter-worker"}},
                "spec": {"nodeName": name, "containers": [{"name": "w"}]},
                "status": {"phase": "Running",
                           "podIP": self._ip_by_node[name]},
            })

        def client_factory(address: str):
            ip = address.rsplit(":", 1)[0]
            return WorkerClient(f"localhost:{self._port_by_ip[ip]}",
                                cfg=self.cfg,
                                channel_pool=self.channel_pool)

        self.app = MasterApp(self.cluster.kube, cfg=self.cfg,
                             worker_client_factory=client_factory,
                             registry=WorkerRegistry(self.cluster.kube,
                                                     self.cfg))
        return self

    def restart_worker(self, name: str) -> dict:
        """Simulate a worker crash + restart on one node: abandon the
        old process's ledger fd (no clean-shutdown marker), rebuild the
        whole service over the same ledger dir, run the startup replay,
        and serve on a fresh port. Returns the replay summary."""
        from gpumounter_tpu.worker.resync import LedgerResync
        old = self.services[name]
        if old.ledger is not None:
            old.ledger.abandon()
        # Process death takes the in-process policy engine with it:
        # drop this node's scopes so the ledger replay must re-arm
        # them (a fresh worker process starts from an empty table —
        # the engine is process-global only because the harness runs
        # every "process" in one).
        from gpumounter_tpu.cgroup.policy import POLICY_ENGINE
        for (ns, pod_name), node_of in self.pods.items():
            if node_of == name:
                POLICY_ENGINE.drop_scope(f"{ns}/{pod_name}")
        service = self._build_node_service(name)
        summary = LedgerResync(service).replay_once()
        self._serve_node(name, service)
        self.record(f"restart worker {name}: replay {summary}")
        return summary

    def kill_node(self, name: str) -> None:
        """Node death as the control plane sees it: the worker's gRPC
        endpoint refuses, its pod is gone from the registry, and the
        Node object goes NotReady. (The backing state — device dirs,
        ledger — stays on disk, exactly like dead hardware.)"""
        server = self._servers.pop(name, None)
        if server is not None:
            server.stop(grace=None)
        self.channel_pool.invalidate(
            f"localhost:{self._port_by_ip.get(self._ip_by_node[name])}",
            "node-kill")
        self.cluster.kube.delete_pod(self.cfg.worker_namespace,
                                     f"chaos-worker-{name}")
        self.cluster.kube.set_node_ready(name, False,
                                         reason="KubeletStopped")
        self.dead_nodes.add(name)
        self.record(f"kill node {name}")

    def attach_tenant(self, namespace: str, pod: str,
                      extra_pods: tuple = (),
                      publish_url: str | None = None,
                      token: str | None = None) -> TenantSim:
        """Run a fake tenant (step loop + real jaxside watchers) for an
        existing target pod; arms invariant 13."""
        sim = TenantSim(self.cluster.kube, namespace, pod,
                        extra_pods=extra_pods, publish_url=publish_url,
                        token=token)
        self.tenant_sims[(namespace, pod)] = sim
        return sim

    def stop_tenants(self) -> None:
        for sim in self.tenant_sims.values():
            sim.stop()

    def stop(self) -> None:
        failpoints.disarm_all()
        self.stop_tenants()
        if self.watch_store is not None:
            self.watch_store.stop()
        if self.app is not None:
            self.app.recovery.stop()
            self.app.elastic.stop()
            self.app.migrations.stop()
            self.app.registry.stop()
        self.channel_pool.close_all()
        for server in self._servers.values():
            server.stop(grace=None)
        self.cluster.stop()

    def __enter__(self) -> "ChaosHarness":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- plumbing ---

    def record(self, event: str) -> None:
        self.schedule.append(event)
        logger.info("chaos[seed=%d] %s", self.seed, event)

    def drop_worker_spans(self) -> int:
        """NEGATIVE CONTROL for invariant 16: rewrite BOTH span stores
        (the local ring and the federated remote store — the collector
        pass of invariant 8 legitimately mirrors worker spans there)
        without any worker-side spans, simulating a worker whose span
        export was silently lost everywhere. check_invariants() must
        then flag every traced op as incomplete assembly. Returns the
        number of spans dropped."""
        from gpumounter_tpu.obs.assembly import REMOTE_SPANS
        ring = trace.TRACER.ring
        spans = ring.snapshot()
        kept = [s for s in spans
                if not s.get("name", "").startswith("worker.")]
        ring.clear()
        for span in kept:
            ring.export(span)
        dropped = len(spans) - len(kept)
        remote = REMOTE_SPANS.snapshot()
        REMOTE_SPANS.reset()
        for span in remote:
            if span.get("name", "").startswith("worker."):
                dropped += 1
                continue
            REMOTE_SPANS.ingest(span.get("node", ""), [span])
        return dropped

    def withhold_unmount(self, node_name: str = NODE_A) -> str | None:
        """NEGATIVE CONTROL for invariant 17: silently erase one held
        chip from the fake kubelet's claims WITHOUT unmounting it or
        touching the scheduler's assignment — exactly the divergence a
        lost/withheld unmount would leave (the worker-side capacity
        snapshot reports the chip free while the ground-truth books
        still hold it). check_invariants() must flag it as capacity
        divergence. Returns the tampered chip id (None when the node
        holds nothing)."""
        node = self.cluster.node(node_name)
        with self.cluster._alloc_lock:
            victim = next(
                (cid for cid, owner in sorted(node.assignment.items(),
                                              key=lambda kv: int(kv[0]))
                 if owner is not None and cid not in node.dead), None)
            if victim is None:
                return None
            trimmed = []
            for pod, ns, container, resource, ids in node.kubelet.claims:
                kept = [i for i in ids if i != victim]
                if kept or not ids:
                    trimmed.append((pod, ns, container, resource, kept))
            node.kubelet.claims = trimmed
        self.record(f"withhold unmount of chip {victim} on {node_name} "
                    f"(kubelet claim erased, booking kept)")
        return victim

    def add_pod(self, name: str, node: str, namespace: str = "default",
                ) -> Pod:
        pod = self.cluster.add_target_pod(name, namespace=namespace,
                                          node=node)
        self.pods[(namespace, name)] = node
        return pod

    def _coordinator(self):
        from gpumounter_tpu.master.slice_ops import SliceCoordinator
        return SliceCoordinator(self.cluster.kube, self.app.registry,
                                self.app._client_factory, self.cfg)

    def _client_for_node(self, node: str) -> WorkerClient:
        address = self.app.registry.worker_address(node)
        return self.app._client_factory(address)

    def probe(self, namespace: str, pod: str):
        node = self.pods[(namespace, pod)]
        with self._client_for_node(node) as client:
            _, chips = client.probe_tpu(pod, namespace)
        return chips

    def _arm_random(self, pool) -> None:
        name, action = self.rng.choice(pool)
        self.record(f"arm {name}={action}")
        failpoints.arm(name, action)

    def _op(self, pool, description: str, fn, fault_p: float = 0.7,
            capture_trace: bool = False) -> None:
        """Run one chaos operation: maybe arm a fault, execute, log the
        outcome, clear any unfired one-shots. With capture_trace, a
        CLEAN run (no fault armed, no exception) executes under a
        chaos.<description> root span and its trace id is recorded for
        invariant 16 — assembly closure is asserted only for
        operations that terminated normally (a crashed op legitimately
        has no worker half to join)."""
        armed = self.rng.random() < fault_p
        if armed:
            self._arm_random(pool)
        ctx = None
        try:
            if capture_trace and not armed:
                with trace.span(f"chaos.{description}") as ctx:
                    fn()
            else:
                fn()
        except Exception as exc:  # noqa: BLE001 — failures ARE the test
            self.record(f"{description} -> {type(exc).__name__}: {exc}")
        else:
            self.record(f"{description} -> ok")
            if ctx is not None:
                self.traced_ops.append({"trace": ctx.trace_id,
                                        "span": ctx.span_id,
                                        "op": description})
        finally:
            failpoints.disarm_all()

    # --- scenarios ---

    def run_mount_scenario(self, n_ops: int = 10) -> None:
        """Imperative add/remove traffic with declared intents as the
        repair substrate: whatever the faults leave behind, converging to
        the intent must restore the safety invariants."""
        from gpumounter_tpu.elastic.intents import Intent
        # Two pods share NODE_A so the double-hold invariant has teeth.
        pods = [("default", "m-a", NODE_A), ("default", "m-b", NODE_B),
                ("default", "m-c", NODE_A)]
        for ns, name, node in pods:
            self.add_pod(name, node, namespace=ns)
            desired = self.rng.randint(1, 2)
            self.app.elastic.store.put(ns, name, Intent(
                desired_chips=desired, min_chips=1))
            self.record(f"intent {ns}/{name} desired={desired}")
        from gpumounter_tpu.master.slice_ops import SliceTarget
        for _ in range(n_ops):
            ns, name, node = self.rng.choice(pods)
            kind = self.rng.choice(["add", "remove", "reconcile"])
            if kind == "add":
                n = self.rng.randint(1, 2)
                self._op(FAULTS_COMMON, f"add {n} to {name}",
                         lambda t=SliceTarget(namespace=ns, pod=name), n=n:
                         self._coordinator().mount_slice([t], n,
                                                         entire=False),
                         capture_trace=True)
            elif kind == "remove":
                held = [c.uuid for c in self.probe(ns, name)]
                if not held:
                    continue
                uuid = self.rng.choice(held)

                def _remove(ns=ns, name=name, node=node, uuid=uuid):
                    with self._client_for_node(node) as client:
                        client.remove_tpu(name, ns, [uuid], force=True)

                self._op(FAULTS_COMMON, f"remove {uuid} from {name}",
                         _remove, capture_trace=True)
            else:
                self._op(FAULTS_ELASTIC, f"reconcile {name}",
                         lambda ns=ns, name=name:
                         self.app.elastic.reconcile_once(ns, name))
        self.converge()

    def run_elastic_scenario(self, n_ops: int = 10) -> None:
        """Declarative convergence under chip deaths and induced faults."""
        from gpumounter_tpu.elastic.intents import Intent
        pods = [("default", "e-a", NODE_A), ("default", "e-b", NODE_B)]
        for ns, name, node in pods:
            self.add_pod(name, node, namespace=ns)
            self.app.elastic.store.put(ns, name, Intent(
                desired_chips=2, min_chips=1))
        kills = 0
        for _ in range(n_ops):
            ns, name, node = self.rng.choice(pods)
            roll = self.rng.random()
            if roll < 0.2 and kills < 2:
                # Kill a chip the pod currently holds (if any): the heal
                # path must converge through it.
                held = self.probe(ns, name)
                if held:
                    victim = self.rng.choice(held)
                    index = next(
                        (str(d.index) for d in
                         self.cluster.node(node).backend.list_devices()
                         if d.uuid == victim.uuid), None)
                    if index is not None:
                        self.record(f"kill chip {victim.uuid} on {node}")
                        self.cluster.kill_chip(index, node)
                        kills += 1
                        continue
            amount = self.rng.choice([1, 2, 3])
            if roll < 0.35:
                self.record(f"intent {name} desired={amount}")
                self.app.elastic.store.put(ns, name, Intent(
                    desired_chips=amount, min_chips=1))
            self._op(FAULTS_ELASTIC, f"reconcile {name}",
                     lambda ns=ns, name=name:
                     self.app.elastic.reconcile_once(ns, name))
        self.converge()

    def run_migrate_scenario(self, n_migrations: int = 2) -> None:
        """Live migrations with crashes at journal-phase boundaries; every
        journal must reach a terminal state via resume_interrupted."""
        from gpumounter_tpu.master.slice_ops import SliceTarget
        self.add_pod("src", NODE_A)
        self.add_pod("dst", NODE_B)
        self._coordinator().mount_slice(
            [SliceTarget(namespace="default", pod="src")], 2, entire=False)
        self.record("mounted 2 chips on default/src")
        source, dest = ("default", "src"), ("default", "dst")
        for _ in range(n_migrations):
            if self.rng.random() < 0.8:
                self._arm_random(FAULTS_MIGRATE)
            # Half the traffic takes the v2 checkpoint-assisted drain:
            # with no tenant watcher attached the checkpoint ack times
            # out and the machine must degrade to the classic drain —
            # under the same crash faults as every other phase.
            checkpoint = self.rng.random() < 0.5
            try:
                journal = self.app.migrations.begin(
                    source[0], source[1], dest[0], dest[1],
                    checkpoint=checkpoint)
            except Exception as exc:  # noqa: BLE001 — rejection is fine
                self.record(f"migrate begin -> {type(exc).__name__}: {exc}")
                failpoints.disarm_all()
                continue
            mid = journal["id"]
            self.record(f"migrate {mid}: {source[1]} -> {dest[1]}")
            self._drive_to_terminal(mid)
            failpoints.disarm_all()
            final = self.app.migrations.get(mid) or {}
            self.record(f"migrate {mid} -> {final.get('outcome')}")
            if final.get("outcome") == "succeeded":
                source, dest = dest, source  # ping-pong back
        self.converge()

    def seed_fragmentation(self) -> None:
        """Fragment NODE_A so a 4-chip block is infeasible there
        despite 4 free chips, and provision the standby destination on
        NODE_B — the setup the defrag scenario and the verdict-flip
        test both build on."""
        from gpumounter_tpu.defrag import ANNOT_DEFRAG_DEST
        from gpumounter_tpu.master.slice_ops import SliceTarget
        # Placement packs blocks in order, so df-pad takes [0,1] and
        # df-keep [2,3]; freeing df-pad leaves NODE_A free {0,1,4,5} —
        # 4 free chips but largest ICI block 2: blocked for a 4-block
        # until df-keep's middle block moves out.
        # Healthy history first: the slice-feasibility SLO is a ratio
        # over per-pass feasibility evaluations, and the controller
        # hard-gates on its burn. In a real fleet one fragmentation
        # event sits in hours of clean passes; compressed test time has
        # to provide those passes explicitly or the single fragmented
        # collect IS the whole window and the gate (correctly) refuses.
        for _ in range(20):
            self.app.fleet.refresh_if_stale(0.0)
        self.add_pod("df-pad", NODE_A)
        self.add_pod("df-keep", NODE_A)
        coordinator = self._coordinator()
        coordinator.mount_slice(
            [SliceTarget(namespace="default", pod="df-pad")], 2,
            entire=False)
        coordinator.mount_slice(
            [SliceTarget(namespace="default", pod="df-keep")], 2,
            entire=False)
        pad_held = [c.uuid for c in self.probe("default", "df-pad")]
        with self._client_for_node(NODE_A) as client:
            client.remove_tpu("df-pad", "default", pad_held, force=True)
        self.record("fragmented NODE_A: df-keep holds the middle block")
        # The operator-provisioned standby destination on NODE_B: a
        # Running pod annotated tpumounter.io/defrag-dest is the only
        # thing the controller will ever mount a moved tenant into.
        self.add_pod("df-standby", NODE_B)
        self.cluster.kube.patch_pod("default", "df-standby", {
            "metadata": {"annotations": {ANNOT_DEFRAG_DEST: "ready"}}})
        self.app.fleet.refresh_if_stale(0.0)

    def run_defrag_scenario(self, target_block: int = 4) -> dict:
        """Fragment NODE_A so a target_block slice is infeasible there
        despite enough free chips, then let the REAL defrag controller
        plan and execute the recovery (checkpoint-assisted moves to an
        operator-provisioned standby on NODE_B). check_invariants()
        then also asserts invariant 18 over the recorded run."""
        self.seed_fragmentation()
        plan = self.app.defrag.plan(target_block=target_block)
        self.record(f"defrag plan {plan['id']}: {len(plan['moves'])} "
                    f"move(s), predicted fragmentation "
                    f"{plan['fragmentation_before']} -> "
                    f"{plan['fragmentation_after']}")
        self.app.defrag.run(plan["id"], wait=True)
        run = self.app.defrag.payload()["history"][-1]
        self.defrag_runs.append(run)
        self.record(f"defrag run {run['plan_id']} -> {run['status']}")
        self.converge()
        return run

    # --- invariant 10: worker crash mid-batch + ledger replay ---

    #: crash sites inside the worker's mount batch, i.e. the windows a
    #: real worker process death can land in (ledger txn already open).
    CRASH_SITES = [
        ("worker.mount.before_grant", "1*crash(chaos worker death)"),
        ("worker.mount.after_grant", "1*crash(chaos worker death)"),
        ("worker.mount.mknod", "1*crash(chaos worker death)"),
        ("worker.mount.mknod", "1*pass->1*crash(chaos worker death 2nd)"),
    ]

    def run_worker_crash_scenario(self, n_ops: int = 8) -> None:
        """Seeded worker crashes inside mount batches, each followed by
        a worker restart + ledger replay; interleaved with healthy
        traffic. check_invariants() then also asserts invariant 10:
        books == mounts == ledger on every node."""
        from gpumounter_tpu.elastic.intents import Intent
        from gpumounter_tpu.master.slice_ops import SliceTarget
        self.check_ledgers = True
        pods = [("default", "wc-a", NODE_A), ("default", "wc-b", NODE_B),
                ("default", "wc-c", NODE_A)]
        for ns, name, node in pods:
            self.add_pod(name, node, namespace=ns)
            desired = self.rng.randint(1, 2)
            self.app.elastic.store.put(ns, name, Intent(
                desired_chips=desired, min_chips=1))
            self.record(f"intent {ns}/{name} desired={desired}")
        for _ in range(n_ops):
            ns, name, node = self.rng.choice(pods)
            roll = self.rng.random()
            if roll < 0.5:
                # Crash the worker mid-batch, then restart + replay.
                site, action = self.rng.choice(self.CRASH_SITES)
                self.record(f"arm {site}={action}")
                failpoints.arm(site, action)
                n = self.rng.randint(1, 2)
                try:
                    self._coordinator().mount_slice(
                        [SliceTarget(namespace=ns, pod=name)], n,
                        entire=False)
                except Exception as exc:  # noqa: BLE001 — the crash
                    self.record(f"crash-mount {n} to {name} -> "
                                f"{type(exc).__name__}")
                else:
                    self.record(f"crash-mount {n} to {name} -> ok "
                                f"(fault unfired)")
                finally:
                    failpoints.disarm_all()
                self.restart_worker(node)
            elif roll < 0.75:
                n = self.rng.randint(1, 2)
                self._op([], f"add {n} to {name}",
                         lambda t=SliceTarget(namespace=ns, pod=name),
                         n=n: self._coordinator().mount_slice(
                             [t], n, entire=False), fault_p=0.0)
            else:
                self._op([], f"reconcile {name}",
                         lambda ns=ns, name=name:
                         self.app.elastic.reconcile_once(ns, name),
                         fault_p=0.0)
        self.converge()

    # --- invariant 19: fractional shares — books == policy == ledger ---

    #: the two co-located share tenants the scenario drives:
    #: (namespace, pod, profile, weight, rate budget). Weights and
    #: budgets are fixed per tenant so the probe-driven books resync is
    #: idempotent; the decode tenant is metered (finite budget) so the
    #: throttle-parity check always has a share to drive.
    SHARE_TENANTS = [
        ("default", "vc-prefill", "prefill", 60, 0),
        ("default", "vc-decode", "decode", 40, 64),
    ]

    #: share-op fault pool: no crash actions — worker crashes are
    #: driven explicitly (crash + restart + replay, like invariant 10)
    #: so an open mount txn never survives into the invariant check.
    FAULTS_SHARE = [
        ("rpc.client.call", "1*unavailable(chaos drop)"),
        ("rpc.client.call", "1*delay(0.05)"),
        ("worker.rpc", "1*delay(0.05)"),
        ("worker.mount.mknod", "1*error(chaos mknod)"),
        ("worker.unmount.before_revoke", "1*error(chaos revoke)"),
        ("k8s.patch_pod.status", "1*return(409)"),
    ]

    def run_share_scenario(self, n_ops: int = 10) -> None:
        """Fractional (vchip) share traffic under faults: two
        complementary tenants mount policy-carrying grants on NODE_A
        through the real RPC path (share_weight/share_rate_budget on
        the wire -> worker mount_many(policy=...) -> ledger share
        records + policy engine entries), the master share registry is
        resynced from probe ground truth after every op, worker
        crashes are followed by restart + ledger replay (the
        fractional replay re-arms the policy engine), and releases
        clear all three ledgers. check_invariants() then asserts
        invariant 19."""
        self.vchip_armed = True
        self.check_ledgers = True
        for ns, name, _profile, _w, _b in self.SHARE_TENANTS:
            self.add_pod(name, NODE_A, namespace=ns)
        for _ in range(n_ops):
            ns, name, profile, weight, budget = self.rng.choice(
                self.SHARE_TENANTS)
            roll = self.rng.random()
            if roll < 0.25:
                # Worker crash mid-fractional-mount, restart + replay:
                # the replay either completes the policy-carrying grant
                # (ledger + engine re-armed) or rolls it back cleanly.
                site, action = self.rng.choice(self.CRASH_SITES)
                self.record(f"arm {site}={action}")
                failpoints.arm(site, action)
                try:
                    self._share_mount(ns, name, weight, budget)
                except Exception as exc:  # noqa: BLE001 — the crash
                    self.record(f"crash share-mount {name} -> "
                                f"{type(exc).__name__}")
                else:
                    self.record(f"crash share-mount {name} -> ok "
                                f"(fault unfired)")
                finally:
                    failpoints.disarm_all()
                self.restart_worker(NODE_A)
            elif roll < 0.55:
                self._op(self.FAULTS_SHARE, f"share-mount {name}",
                         lambda ns=ns, name=name, weight=weight,
                         budget=budget:
                         self._share_mount(ns, name, weight, budget))
            elif roll < 0.8:
                held = [c.uuid for c in self.probe(ns, name)]
                if held:
                    uuid = self.rng.choice(held)

                    def _release(ns=ns, name=name, uuid=uuid):
                        with self._client_for_node(NODE_A) as client:
                            client.remove_tpu(name, ns, [uuid],
                                              force=True)

                    self._op(self.FAULTS_SHARE,
                             f"share-release {uuid} from {name}",
                             _release)
            else:
                # Warm re-grant: re-book a held share in place — the
                # O(1) map_update path on the books side (no new slot).
                held = [c.uuid for c in self.probe(ns, name)]
                if held:
                    from gpumounter_tpu.vchip.shares import Share
                    uuid = self.rng.choice(held)
                    self.app.shares.add(Share(
                        namespace=ns, pod=name, chip_uuid=uuid,
                        node=NODE_A, weight=weight, rate_budget=budget,
                        profile=profile))
                    self.record(f"re-grant {ns}/{name}/{uuid}")
            self._sync_share_books(ns, name, profile, weight, budget)
        # The throttle-parity check needs a metered share to exist:
        # make sure the decode tenant ends holding at least one chip
        # (freeing a prefill chip first if the node is full).
        ns, name, profile, weight, budget = self.SHARE_TENANTS[1]
        if not self.probe(ns, name):
            result, _uuids = self._share_mount(ns, name, weight, budget)
            if result.name != "Success":
                p_ns, p_name = self.SHARE_TENANTS[0][:2]
                p_held = [c.uuid for c in self.probe(p_ns, p_name)]
                if p_held:
                    with self._client_for_node(NODE_A) as client:
                        client.remove_tpu(p_name, p_ns, [p_held[0]],
                                          force=True)
                    self._sync_share_books(
                        p_ns, p_name, *self.SHARE_TENANTS[0][2:])
                self._share_mount(ns, name, weight, budget)
            self._sync_share_books(ns, name, profile, weight, budget)
        # One final clean restart: the fractional-replay leg — share
        # policies must survive a worker restart via the ledger
        # (resync._replay_share_policies re-arms the engine).
        summary = self.restart_worker(NODE_A)
        if not summary.get("share_policies_replayed"):
            self.record("WARNING: restart replayed no share policies")
        for ns, name, profile, weight, budget in self.SHARE_TENANTS:
            self._sync_share_books(ns, name, profile, weight, budget)
        self.converge()

    def _share_mount(self, ns: str, name: str, weight: int,
                     budget: int, n: int = 1):
        """One fractional mount through the real RPC path; returns
        (result, uuids)."""
        with self._client_for_node(NODE_A) as client:
            result, uuids = client.add_tpu_detailed(
                name, ns, n, share_weight=weight,
                share_rate_budget=budget)
        self.record(f"share-mount {ns}/{name} w={weight} b={budget} "
                    f"-> {result.name} {uuids}")
        return result, uuids

    def _sync_share_books(self, ns: str, name: str, profile: str,
                          weight: int, budget: int) -> None:
        """Reconcile the master share registry to the worker's ground
        truth for one tenant — the probe-driven resync a production
        share controller runs after faults (the registry is a books
        plane; the worker's ledger + policy engine are authoritative
        for what is actually granted)."""
        from gpumounter_tpu.vchip.shares import Share
        held = {c.uuid for c in self.probe(ns, name)}
        booked = {s.chip_uuid
                  for s in self.app.shares.by_tenant(ns, name)}
        for uuid in sorted(held - booked):
            self.app.shares.add(Share(
                namespace=ns, pod=name, chip_uuid=uuid,
                node=self.pods[(ns, name)], weight=weight,
                rate_budget=budget, profile=profile))
        for uuid in sorted(booked - held):
            self.app.shares.remove(ns, name, uuid)

    def disable_enforcement(self) -> None:
        """NEGATIVE CONTROL for invariant 19: flip the userspace policy
        engine into pure-bookkeeper mode (admits everything once the
        budget is exhausted, exactly what a broken enforcement path
        would do). The decision procedure now diverges from the
        in-kernel program the interpreter executes, and
        check_invariants() must flag the disagreement."""
        from gpumounter_tpu.cgroup.policy import POLICY_ENGINE
        POLICY_ENGINE.enforce = False
        self.record("negative control: policy enforcement disabled")

    # --- invariant 20: gray failure -> scoring -> quarantine ---

    #: probabilistic degradation armed ONLY while operating against the
    #: limping node: a gray failure is intermittent slowness, not an
    #: outage — deterministic delay() would make every call slow (a
    #: liveness failure the recovery controller already catches); these
    #: draws come from the seeded failpoint RNG, so the limp reproduces.
    GRAY_FAULTS = [
        ("worker.mount.mknod", "pdelay([0.9, 0.2])"),
        ("worker.rpc", "pdelay([0.5, 0.06])"),
        ("rpc.client.call", "pdrop(0.2)"),
    ]

    def run_gray_scenario(self, limping: tuple = (NODE_B,),
                          n_rounds: int = 4, mounts_per_round: int = 3,
                          disable_scorer: bool = False) -> dict:
        """Drive real mount/unmount traffic through the full worker path
        on every node, with probabilistic degradation (GRAY_FAULTS)
        armed only around the limping nodes' operations, and feed the
        REAL health plane per-node scoring passes built from the
        harness's own wall-clock measurements of those operations.

        The harness must measure per-node latency itself because every
        fake node shares one process — and therefore one global metrics
        registry, which folds all nodes' mount histograms together. In
        production each worker is its own process and CollectTelemetry
        returns genuinely per-node stats; the measured numbers here are
        the same real operations, bucketed by the node that served them.

        Needs >= 4 nodes (3-node healthy herd) so the fleet median is a
        healthy number the outlier bar can stand on.

        disable_scorer=False: the limping node must end quarantined and
        check_invariants() proves the attribution trail (invariant 20).
        disable_scorer=True is the NEGATIVE CONTROL: the plane is
        switched off while the node limps, the quarantine never
        happens, and invariant 20 must DETECT the missed detection.

        Returns {"states": final pane per node, "passes": scoring
        passes driven}."""
        if len(self.services) < 4:
            raise ValueError(
                "run_gray_scenario needs a >=4-node cluster "
                "(3-node healthy herd for the fleet median); build the "
                "harness with nodes={...4 entries...}")
        failpoints.seed(self.seed)
        self.gray_armed = True
        self.gray_nodes.update(limping)
        # Fast-hysteresis health knobs at test speed; the plane stays
        # OFF for the negative control (its observe() is a no-op, the
        # exact failure mode of a disabled/broken scorer).
        self.app.health.cfg = self.cfg.replace(
            health_enabled=not disable_scorer,
            health_min_samples=3,
            health_p95_multiplier=3.0,
            health_p95_floor_ms=20.0,
            health_suspect_strikes=2,
            health_quarantine_strikes=3,
            health_clear_passes=2)
        if disable_scorer:
            self.record("negative control: health scorer disabled")
        pods_by_node: dict[str, tuple[str, str]] = {}
        for i, node in enumerate(sorted(self.services)):
            name = f"gf-{i}"
            self.add_pod(name, node)
            pods_by_node[node] = ("default", name)
        samples: dict[str, list[float]] = {n: [] for n in pods_by_node}
        errors: dict[str, int] = {n: 0 for n in pods_by_node}
        passes = 0
        for _round in range(n_rounds):
            for node, (ns, name) in sorted(pods_by_node.items()):
                for _ in range(mounts_per_round):
                    if node in limping:
                        for site, action in self.GRAY_FAULTS:
                            failpoints.arm(site, action)
                    started = time.monotonic()
                    ok = False
                    try:
                        with self._client_for_node(node) as client:
                            result, uuids = client.add_tpu_detailed(
                                name, ns, 1)
                        ok = result.name == "Success"
                        if ok and uuids:
                            with self._client_for_node(node) as client:
                                client.remove_tpu(name, ns, list(uuids),
                                                  force=True)
                    except Exception as exc:  # noqa: BLE001 — the limp
                        self.record(f"gray mount on {node} -> "
                                    f"{type(exc).__name__}")
                    finally:
                        failpoints.disarm_all()
                    samples[node].append(
                        (time.monotonic() - started) * 1000.0)
                    if not ok:
                        errors[node] += 1
            self.app.health.observe(self._gray_entries(samples, errors))
            passes += 1
            states = {n: p["state"] for n, p in
                      self.app.health.payload()["nodes"].items()}
            self.record(f"gray pass {passes}: {states}")
        self.converge()
        return {"states": {n: p["state"] for n, p in
                           self.app.health.payload()["nodes"].items()},
                "passes": passes}

    def _gray_entries(self, samples: dict[str, list[float]],
                      errors: dict[str, int]) -> dict[str, dict]:
        """Per-node CollectTelemetry-shaped entries from the harness's
        own measurements (see run_gray_scenario for why)."""
        entries: dict[str, dict] = {}
        for node, vals in samples.items():
            if node in self.dead_nodes:
                continue
            ordered = sorted(vals)
            p95 = (ordered[min(len(ordered) - 1,
                               int(0.95 * len(ordered)))]
                   if ordered else None)
            entries[node] = {
                "mount": {"count": len(vals), "p95_ms": p95,
                          "success": len(vals) - errors[node],
                          "error": errors[node]},
                "breaker": "closed",
            }
        return entries

    # --- invariant 21: autoscale decision closure ---

    class _TenantOverlayFleet:
        """Real fleet rollup + harness-simulated tenant telemetry.

        The autoscaler reads tenant snapshots out of the fleet node
        entries (the /tenants path). Every fake node here runs in ONE
        process, so real per-tenant step telemetry can't ride the
        worker RPC per node; like _gray_entries for the health plane,
        the harness fabricates the tenant sections itself — on top of
        the REAL collected rollup, so capacity/health stay genuine."""

        def __init__(self, fleet, tenants_by_node):
            self.fleet = fleet
            self.tenants_by_node = tenants_by_node

        def payload(self, max_age_s=None):
            rollup = self.fleet.payload(max_age_s=max_age_s)
            for node, snaps in self.tenants_by_node.items():
                entry = rollup.get("nodes", {}).get(node)
                if entry is not None:
                    entry["tenants"] = {
                        t: dict(s) for t, s in snaps.items()}
            return rollup

    def run_autoscale_scenario(self, n_passes: int = 8,
                               disable_gates: bool = False) -> dict:
        """Drive the REAL autoscale controller over the live harness:
        one saturated tenant (deep queue, rate pinned to its learned
        plateau) that must be grown, one idle tenant (empty queue, low
        utilization) that must be shrunk to its floor — with elastic
        faults armed around the reconciles that actuate the decisions.

        disable_gates=True is the NEGATIVE CONTROL: enforcement off
        while the controller is operator-paused, so decisions fire
        through a recorded-closed gate — invariant 21 must DETECT it.

        Returns {"passes": pass records, "fired": decision count}."""
        from gpumounter_tpu.autoscale import AutoscaleRefused
        from gpumounter_tpu.elastic.intents import Intent
        failpoints.seed(self.seed)
        self.autoscale_armed = True
        ctrl = self.app.autoscale
        # Test-speed knobs: no cooldown (a pass is a simulated interval,
        # not 60 real seconds); everything else at production defaults.
        ctrl.cfg = self.cfg.replace(autoscale_cooldown_s=0.0)
        ctrl.model.cfg = ctrl.cfg
        pods = [("default", "as-grow", NODE_A, 2, 50.0, 160.0),
                ("default", "as-shrink", NODE_B, 3, 0.0, 3.0)]
        tenants_by_node: dict[str, dict[str, dict]] = {}
        cumulative: dict[str, dict] = {}
        for ns, name, node, desired, queue, batch in pods:
            self.add_pod(name, node, namespace=ns)
            self.autoscale_pods.append((ns, name))
            self.app.elastic.store.put(ns, name, Intent(
                desired_chips=desired, min_chips=1))
            self.app.elastic.reconcile_once(ns, name)
            cumulative[f"{ns}/{name}"] = {
                "node": node, "steps": 0.0, "tokens": 0.0,
                "queue": queue, "batch": batch}
        ctrl.fleet = self._TenantOverlayFleet(self.app.fleet,
                                              tenants_by_node)
        if disable_gates:
            ctrl.enforce_gates = False
            ctrl.pause(actor="chaos-negative-control")
            self.record("negative control: autoscale gate enforcement "
                        "disabled while operator-paused")
        fired = 0
        for n in range(n_passes):
            for tenant, state in sorted(cumulative.items()):
                # batches wiggle around the profile so the fit sees
                # curvature; rates sit ON rate = 100*b/(b+10), keeping
                # each tenant's utilization at its designed regime
                batch = state["batch"] * (1.0 + 0.25 * self.rng.random())
                rate = 100.0 * batch / (batch + 10.0)
                state["steps"] += 1.0
                state["tokens"] += batch
                tenants_by_node.setdefault(state["node"], {})[tenant] = {
                    "steps": {"count": state["steps"]},
                    "tokens_total": state["tokens"],
                    "tokens_per_s": rate,
                    "queue_depth": state["queue"],
                    "at": time.time(),
                }
            try:
                record = ctrl.evaluate_once()
            except AutoscaleRefused as exc:
                self.record(f"autoscale pass {n} refused: {exc.cause}")
                continue
            self.autoscale_passes.append(record)
            for decision in record["decisions"]:
                if decision["action"] not in ("grow", "shrink"):
                    continue
                fired += 1
                self.record(
                    f"autoscale {decision['action']} "
                    f"{decision['tenant']}: {decision['from_chips']} -> "
                    f"{decision['to_chips']}")
                ns, name = decision["namespace"], decision["pod"]
                self._op(FAULTS_ELASTIC, f"reconcile {name}",
                         lambda ns=ns, name=name:
                         self.app.elastic.reconcile_once(ns, name))
        self.converge()
        return {"passes": self.autoscale_passes, "fired": fired}

    # --- invariant 22: watch-store index parity under stream chaos ---

    #: fictional hosts the watch-store churn schedules pool pods onto —
    #: disjoint from the real worker nodes so nothing else (recovery,
    #: health, bookings) ever operates on the churned population.
    WS_POOL_NODES = ("wsnode-1", "wsnode-2", "wsnode-3")
    WS_ANCHORS = ("ws-anchor-a", "ws-anchor-b")

    def run_watch_store_scenario(self, churn_per_round: int = 40,
                                 storm_events: int = 120) -> dict:
        """Build the watch/informer-backed store over the live cluster
        and batter its event stream with the three failure shapes the
        informer protocol must survive, in seeded order: a severed
        watch plus a churn storm far past a shrunken event backlog (so
        the resume's resourceVersion has honestly expired — a 410
        Gone), a full master restart (stop + fresh instance = relist
        from scratch), and plain steady churn. check_invariants() then
        holds invariant 22: the store's in-memory indexes agree
        EXACTLY with a fresh list-backed view of the same cluster.

        Returns {"rounds": flavor order, "payload": store diagnostics}.
        """
        from gpumounter_tpu.store import WatchMasterStore
        failpoints.seed(self.seed)
        self.watchstore_armed = True
        kube = self.cluster.kube
        # Shrink the fake apiserver's watch backlog: a storm round's
        # churn must genuinely expire the informer's resourceVersion
        # so its next resume is an honest 410 (the path under test).
        kube._max_events = 64
        self._watch_cfg = self.cfg.replace(store_watch_timeout_s=0.2,
                                           store_watch_relist_base_s=0.02,
                                           store_watch_relist_cap_s=0.2)
        for anchor in self.WS_ANCHORS:
            # Persistent write targets: intent/journal writes THROUGH
            # the store land here (never deleted by the churn).
            kube.create_pod("default", {
                "metadata": {"name": anchor, "namespace": "default"},
                "spec": {"nodeName": self.WS_POOL_NODES[0],
                         "containers": [{"name": "c"}]},
                "status": {"phase": "Running", "podIP": "10.99.0.1"},
            })
        self.watch_store = WatchMasterStore(kube, self._watch_cfg)
        if not self.watch_store.wait_synced(10.0):
            raise InvariantViolation(
                f"watch store never primed (seed={self.seed})")
        self.record("watch store primed (invariant 22 armed)")
        flavors = ["storm", "restart", "steady"]
        self.rng.shuffle(flavors)
        relists_total = 0  # across instances (the restart replaces one)
        for n, flavor in enumerate(flavors):
            self.record(f"watch round {n}: {flavor}")
            if flavor == "storm":
                kube.set_partitioned(True, mode="reads")
                time.sleep(0.3)  # the 0.2s watch window expires; the
                # re-open fails against the partition — stream severed
                self._watch_churn(storm_events)
                kube.set_partitioned(False)
                self.record(f"healed after {storm_events}-event storm "
                            f"(backlog 64: the resume must 410)")
                # Wait out the 410 -> re-LIST recovery HERE: a restart
                # round right behind the heal would otherwise stop the
                # instance mid-recovery and the storm proves nothing.
                self._watch_settle(10.0)
            elif flavor == "restart":
                relists_total += self.watch_store.relists
                self.watch_store.stop()
                self.watch_store = WatchMasterStore(kube,
                                                    self._watch_cfg)
                if not self.watch_store.wait_synced(10.0):
                    raise InvariantViolation(
                        f"watch store never re-primed after restart "
                        f"(seed={self.seed})")
                self.record("watch store restarted (fresh relist)")
                self._watch_churn(churn_per_round)
            else:
                self._watch_churn(churn_per_round)
        # The churned journals are harness-synthetic (no migration
        # machine ran them): clear them through the store so invariant
        # 4's terminal-journal sweep judges only real machines. The
        # clears themselves exercise the annotation-clear write path
        # and overlay retirement one last time.
        from gpumounter_tpu.migrate.journal import ANNOT_JOURNAL
        for anchor in self.WS_ANCHORS:
            self.watch_store.stamp_annotation("default", anchor,
                                              ANNOT_JOURNAL, None)
        self._watch_settle(10.0)
        payload = self.watch_store.payload()
        relists_total += payload["relists"]
        self.record(f"watch store settled: relists={relists_total} "
                    f"events={payload['events_applied']} "
                    f"indexes={payload['indexes']}")
        return {"rounds": flavors, "payload": payload,
                "relists_total": relists_total}

    def _watch_settle(self, timeout_s: float) -> bool:
        """Poll until the watch store's pod index matches the live pod
        count AND the stream has quiesced (a trimmed backlog can only
        be crossed by the 410 -> re-LIST recovery, so this also waits
        that recovery out)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            want = len(self.cluster.kube.list_pods_with_rv()[0])
            if self.watch_store.payload()["indexes"]["pods"] == want \
                    and self.watch_store.quiesce(1.0):
                return True
        return False

    def _watch_churn(self, n_events: int) -> None:
        """Seeded population churn for the watch-store scenario: every
        operation is a WRITE against the fake apiserver (creates,
        annotation patches, reschedules, deletes, plus intent/journal
        writes through the store itself), so storms run cleanly under
        a reads-only partition while the event backlog overflows."""
        from gpumounter_tpu.elastic.intents import Intent
        from gpumounter_tpu.migrate.journal import new_journal
        kube = self.cluster.kube
        pool_ns = self.cfg.pool_namespace
        emitted = 0
        while emitted < n_events:
            roll = self.rng.random()
            if roll < 0.30:  # a new intent-bearing tenant pod
                name = f"ws-{self._ws_serial}"
                self._ws_serial += 1
                kube.create_pod("default", {
                    "metadata": {
                        "name": name, "namespace": "default",
                        "annotations": {"tpumounter.io/desired-chips":
                                        str(self.rng.randint(1, 4))}},
                    "spec": {"nodeName":
                             self.rng.choice(self.WS_POOL_NODES),
                             "containers": [{"name": "c"}]},
                    "status": {"phase": "Running",
                               "podIP": "10.99.0.2"},
                })
                self._ws_default.append(name)
            elif roll < 0.45 and self._ws_default:  # intent flips
                name = self.rng.choice(self._ws_default)
                kube.patch_pod("default", name, {
                    "metadata": {"annotations":
                                 {"tpumounter.io/desired-chips":
                                  str(self.rng.randint(1, 4))}}})
            elif roll < 0.60:  # a new pool pod
                name = f"ws-pool-{self._ws_serial}"
                self._ws_serial += 1
                node = self.rng.choice(self.WS_POOL_NODES)
                kube.create_pod(pool_ns, {
                    "metadata": {"name": name, "namespace": pool_ns},
                    "spec": {"nodeName": node,
                             "containers": [{"name": "c"}]},
                    "status": {"phase": "Running",
                               "podIP": "10.99.0.3"},
                })
                self._ws_pool[name] = node
            elif roll < 0.72 and self._ws_pool:  # pool pod reschedules
                name = self.rng.choice(sorted(self._ws_pool))
                node = self.rng.choice(self.WS_POOL_NODES)
                kube.patch_pod(pool_ns, name,
                               {"spec": {"nodeName": node}})
                self._ws_pool[name] = node
            elif roll < 0.82 and len(self._ws_default) > 2:
                name = self._ws_default.pop(
                    self.rng.randrange(len(self._ws_default)))
                kube.delete_pod("default", name)
            elif roll < 0.90 and len(self._ws_pool) > 1:
                name = sorted(self._ws_pool)[
                    self.rng.randrange(len(self._ws_pool))]
                del self._ws_pool[name]
                kube.delete_pod(pool_ns, name)
            elif roll < 0.96:  # a write THROUGH the store: the
                # read-your-writes overlay works under stream chaos
                anchor = self.rng.choice(self.WS_ANCHORS)
                self.watch_store.put_intent(
                    "default", anchor,
                    Intent(desired_chips=self.rng.randint(1, 4),
                           min_chips=1))
            else:  # a journal save through the store (pure patch)
                src, dst = self.WS_ANCHORS if self.rng.random() < 0.5 \
                    else tuple(reversed(self.WS_ANCHORS))
                journal = new_journal(f"ws-mig-{self._ws_serial}",
                                      "default", src, "default", dst)
                self._ws_serial += 1
                journal["phase"] = "drain"
                self.watch_store.save_journal(journal)
            emitted += 1

    def poison_watch_index(self) -> None:
        """NEGATIVE CONTROL for invariant 22: corrupt one indexed
        intent in place — the stale-cache entry a missed event or a
        buggy overlay merge would leave behind. Nothing changed on the
        API server, so no event, quiesce, or clean stream re-open will
        ever repair it; check_invariants() must flag the divergence."""
        from gpumounter_tpu.elastic.intents import Intent
        store = self.watch_store
        key = ("default", self.WS_ANCHORS[0])
        with store._mu:
            store._intents[key] = Intent(desired_chips=97, min_chips=1)
        self.record(f"negative control: poisoned watch-store intent "
                    f"index for {key[0]}/{key[1]} (stale entry)")

    # --- invariant 11: node kill -> evacuation -> re-convergence ---

    def run_node_kill_scenario(self, n_pods: int = 2) -> dict:
        """Kill NODE_A under live intents: the recovery controller must
        confirm and evacuate it (bookings released), and every stranded
        intent must re-converge on NODE_B once its pod is rescheduled
        there. Returns {"detect_passes", "evacuation", "reconverged"}."""
        from gpumounter_tpu.elastic.intents import Intent
        victims = []
        for i in range(n_pods):
            name = f"nk-{i}"
            self.add_pod(name, NODE_A)
            desired = self.rng.randint(1, 2)
            self.app.elastic.store.put("default", name, Intent(
                desired_chips=desired, min_chips=1))
            victims.append((name, desired))
            outcome = self.app.elastic.reconcile_once("default", name)
            self.record(f"pre-kill {name}: {outcome.get('phase')} "
                        f"desired={desired}")
            if outcome.get("phase") != "converged":
                raise InvariantViolation(
                    f"pre-kill convergence failed for {name}: {outcome}")
        self.add_pod("survivor", NODE_B)
        self.app.elastic.store.put("default", "survivor",
                                   Intent(desired_chips=1, min_chips=1))
        self.app.elastic.reconcile_once("default", "survivor")

        # Prime detection while the node is alive — the production
        # controller loop runs continuously, so every node is tracked
        # BEFORE it can die; a scenario that kills first would race the
        # registry watch evicting the worker and never track the node.
        primed = self.app.recovery.check_once()
        if NODE_A not in self.app.recovery.payload()["nodes"]:
            raise InvariantViolation(
                f"recovery never tracked {NODE_A} while alive: {primed}")
        self.kill_node(NODE_A)
        # Detection loop: drive check_once until the controller commits.
        passes = 0
        evacuated = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not evacuated:
            passes += 1
            out = self.app.recovery.check_once()
            evacuated = NODE_A in out["evacuated"]
            if not evacuated:
                time.sleep(0.05)
        if not evacuated:
            raise InvariantViolation(
                f"node {NODE_A} never evacuated (seed={self.seed}); "
                f"recovery state: {self.app.recovery.payload()}")
        self.record(f"evacuated {NODE_A} after {passes} pass(es)")
        # Bookings on the dead node are gone.
        leftover = [Pod(p).name for p in self.cluster.kube.list_pods(
            self.cfg.pool_namespace)
            if Pod(p).node_name == NODE_A]
        if leftover:
            raise InvariantViolation(
                f"evacuation left bookings on {NODE_A}: {leftover}")

        # The workload controller reschedules each victim onto NODE_B
        # (same name, fresh pod object); intents re-declared by the
        # harness exactly like an annotation-carrying pod template.
        reconverged = {}
        for name, desired in victims:
            self.cluster.kube.delete_pod("default", name)
            self.add_pod(name, NODE_B)
            self.app.elastic.store.put("default", name, Intent(
                desired_chips=desired, min_chips=1))
            deadline = time.monotonic() + 30.0
            outcome: dict = {}
            while time.monotonic() < deadline:
                try:
                    outcome = self.app.elastic.reconcile_once("default",
                                                              name)
                except Exception as exc:  # noqa: BLE001 — keep driving
                    self.record(f"re-drive {name}: retrying ({exc})")
                    time.sleep(0.05)
                    continue
                if outcome.get("phase") == "converged":
                    break
                time.sleep(0.05)
            if outcome.get("phase") != "converged" \
                    or outcome.get("actual") != desired:
                raise InvariantViolation(
                    f"evacuated intent default/{name} never re-converged "
                    f"(seed={self.seed}): {outcome}")
            reconverged[name] = outcome
            self.record(f"re-converged {name} on {NODE_B}: "
                        f"actual={outcome.get('actual')}")
        return {"detect_passes": passes,
                "evacuation": self.app.recovery.payload()["evacuations"],
                "reconverged": reconverged}

    # --- invariant 14: API-server outage -> degraded mode -> heal ---

    def run_api_outage_scenario(self, flavor: str = "mount",
                                replay_enabled: bool = True) -> dict:
        """Flip `fake.set_partitioned` mid-{mount,migrate,heal,recovery}
        and prove invariant 14: during the outage no destructive
        mutation lands from stale reads and no booking leaks; after the
        heal every queued write lands exactly once (newest value wins,
        CAS losers dropped) and books == mounts == ledger == intents.

        replay_enabled=False is the negative control: the write-behind
        flush is disabled, and the scenario must DETECT the resulting
        divergence (queued writes that never landed) by raising
        InvariantViolation."""
        import json as jsonlib
        import threading as threading_mod

        from gpumounter_tpu.elastic.intents import Intent
        from gpumounter_tpu.master.slice_ops import SliceTarget
        assert flavor in ("mount", "migrate", "heal", "recovery"), flavor
        self.check_ledgers = True
        store = self.app.store
        kube_raw = self.cluster.kube
        tracked = self.app.kube  # health-tracked wrapper

        # Converged substrate: one intent-managed pod per node.
        intent_pods = [("default", "ao-a", NODE_A),
                       ("default", "ao-b", NODE_B)]
        desired_by_pod: dict[str, int] = {}
        for ns, name, node in intent_pods:
            self.add_pod(name, node, namespace=ns)
            desired = self.rng.randint(1, 2)
            desired_by_pod[name] = desired
            self.app.elastic.store.put(ns, name, Intent(
                desired_chips=desired, min_chips=1))
            outcome = self.app.elastic.reconcile_once(ns, name)
            if outcome.get("phase") != "converged":
                raise InvariantViolation(
                    f"pre-outage convergence failed for {name}: "
                    f"{outcome}")
            self.record(f"pre-outage {name} converged desired={desired}")

        mode = "writes" if flavor == "heal" else "full"
        mid = None
        dead_uuid = None
        if flavor == "migrate":
            self.add_pod("ao-src", NODE_A)
            self.add_pod("ao-dst", NODE_B)
            self._coordinator().mount_slice(
                [SliceTarget(namespace="default", pod="ao-src")], 2,
                entire=False)
            journal = self.app.migrations.begin(
                "default", "ao-src", "default", "ao-dst")
            mid = journal["id"]
            # Let the machine get PAST begin() — the partition lands
            # mid-migration, with the journal at whatever phase the
            # race reaches.
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                j = self.app.migrations.get(mid) or {}
                if j.get("phase") != "quiesce" or j.get("outcome"):
                    break
                time.sleep(0.005)
            self.record(f"migration {mid} at phase "
                        f"{(self.app.migrations.get(mid) or {}).get('phase')}")
        elif flavor == "heal":
            # A dead chip the reconciler WANTS to heal — but must not
            # touch while the API is unhealthy (stale intent view).
            victim = self.probe("default", "ao-a")[0]
            index = next(str(d.index) for d in
                         self.cluster.node(NODE_A).backend.list_devices()
                         if d.uuid == victim.uuid)
            self.cluster.kill_chip(index, NODE_A)
            dead_uuid = victim.uuid
            self.record(f"killed chip {dead_uuid} on {NODE_A}")
        elif flavor == "recovery":
            # A REAL node death immediately swallowed by the partition:
            # the controller has every reason to evacuate — except that
            # all its evidence is now stale.
            self.app.recovery.check_once()  # track nodes while alive
            self.kill_node(NODE_B)

        if flavor == "mount":
            # Flip the partition MID-mount: the mount thread is inside
            # mount_slice when the API goes away.
            def _racing_mount():
                try:
                    self._coordinator().mount_slice(
                        [SliceTarget(namespace="default", pod="ao-a")],
                        1, entire=False)
                except Exception as exc:  # noqa: BLE001 — the point
                    self.record(f"mid-outage mount -> "
                                f"{type(exc).__name__}")

            racer = threading_mod.Thread(target=_racing_mount,
                                         daemon=True)
            racer.start()
            time.sleep(0.005)
            kube_raw.set_partitioned(True, mode=mode)
            self.record(f"partitioned mid-mount (mode={mode})")
            racer.join(timeout=30.0)
        else:
            kube_raw.set_partitioned(True, mode=mode)
            self.record(f"partitioned (mode={mode}, flavor={flavor})")

        # Drive the health machine to its verdict with real failing
        # calls (the production loops would supply these).
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and self.app.apihealth.ok():
            try:
                if mode == "writes":
                    tracked.patch_pod("default", "ao-a",
                                      {"metadata": {}})
                else:
                    tracked.get_pod("default", "ao-a")
            except Exception:  # noqa: BLE001 — the failures ARE the feed
                pass
            time.sleep(0.01)
        if self.app.apihealth.ok():
            raise InvariantViolation(
                f"api health never left healthy under partition "
                f"(seed={self.seed})")
        self.record(f"api health: {self.app.apihealth.state()}")
        held_at_partition = self.held_chips()

        # --- during the outage ---

        # 1. Annotation writes defer into the durable queue.
        queued_annotations: dict[str, str] = {}
        for i in range(3):
            annot = f"tpumounter.io/outage-test-{i}"
            payload = jsonlib.dumps({"v": i, "flavor": flavor})
            store.stamp_annotation("default", "ao-a", annot, payload)
            queued_annotations[annot] = payload
        # A CAS-carrying write that must LOSE to a newer post-heal
        # writer (seq 1 vs 5).
        store.stamp_annotation(
            "default", "ao-a", "tpumounter.io/outage-cas",
            jsonlib.dumps({"seq": 1, "from": "outage"}))
        if store.queue.pending_count() < len(queued_annotations) + 1:
            raise InvariantViolation(
                f"writes were not deferred during the outage: "
                f"{store.queue.stats()}")
        self.record(f"deferred {store.queue.pending_count()} write(s)")

        # 2. Reconcile passes stay read-only; nothing destructive lands.
        for ns, name, node in intent_pods:
            if node in self.dead_nodes:
                continue
            try:
                outcome = self.app.elastic.reconcile_once(ns, name)
            except Exception as exc:  # noqa: BLE001 — full partition:
                # even the pod GET fails; a failed pass mutates nothing
                self.record(f"outage reconcile {name} -> "
                            f"{type(exc).__name__}")
                continue
            self.record(f"outage reconcile {name} -> "
                        f"{outcome.get('phase')}")
            if outcome.get("healed") or outcome.get("removed_excess") \
                    or outcome.get("added"):
                raise InvariantViolation(
                    f"destructive reconcile during outage: {outcome}")
        if flavor == "heal":
            held_now = self.held_chips()[("default", "ao-a")]
            if dead_uuid not in held_now:
                raise InvariantViolation(
                    f"dead chip {dead_uuid} was removed during the "
                    f"outage (heal must park): held={sorted(held_now)}")

        # 3. Recovery never evacuates during the outage.
        for _ in range(4):
            out = self.app.recovery.check_once()
            if out["evacuated"]:
                raise InvariantViolation(
                    f"evacuation during api outage (stale evidence): "
                    f"{out}")
            time.sleep(0.02)
        if self.app.recovery.payload()["evacuations"]:
            raise InvariantViolation(
                "evacuation recorded during the outage")

        # 4. No mutation landed from stale reads while partitioned.
        if self.held_chips() != held_at_partition:
            raise InvariantViolation(
                f"held chips changed during the outage: "
                f"{held_at_partition} -> {self.held_chips()}")

        # 5. Slave-release deferral (heal flavor: writes partitioned,
        # the unmount itself is node-local): an unmount whose API
        # delete fails must QUEUE the booking, not leak it. Runs after
        # the stale-read snapshot check — this remove is an explicit
        # operator action, not a stale-read mutation.
        if flavor == "heal":
            removable = sorted(self.held_chips()[("default", "ao-b")])
            with self._client_for_node(NODE_B) as client:
                client.remove_tpu("ao-b", "default", [removable[0]],
                                  force=True)
            pending_rel = \
                self.services[NODE_B].ledger.pending_releases()
            if not pending_rel:
                raise InvariantViolation(
                    "slave release during outage neither completed "
                    "nor deferred into the ledger queue")
            self.record(f"slave release deferred: "
                        f"{pending_rel[0].get('pods')}")

        # 6. The migration machine paused (journaled locally), never
        # rolled back mid-outage.
        if mid is not None:
            time.sleep(0.1)  # give the machine a boundary to pause at
            j = self.app.migrations.get(mid) or {}
            if j.get("outcome"):
                raise InvariantViolation(
                    f"migration went terminal during the outage: {j}")
            self.record(f"migration {mid} holding at phase "
                        f"{j.get('phase')} "
                        f"(paused_for_api={j.get('paused_for_api')})")

        # --- heal ---
        kube_raw.set_partitioned(False)
        self.record("partition healed")
        if not replay_enabled:
            # Negative control: break the replay. The divergence below
            # MUST be detected.
            store.flush_writes = lambda: {"applied": 0, "pending":
                                          store.queue.pending_count()}
        # A newer writer advances the CAS counter before our queued
        # seq-1 write can replay.
        kube_raw.patch_pod("default", "ao-a", {
            "metadata": {"annotations": {"tpumounter.io/outage-cas":
                         jsonlib.dumps({"seq": 5,
                                        "from": "post-heal"})}}})
        # Drive recovery with real successful calls on BOTH planes.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline \
                and not self.app.apihealth.ok():
            try:
                tracked.get_pod("default", "ao-a")
                tracked.patch_pod("default", "ao-a", {"metadata": {}})
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.01)
        if not self.app.apihealth.ok():
            raise InvariantViolation("api health never recovered after "
                                     "the partition healed")
        flush = store.flush_writes()
        self.record(f"post-heal flush: {flush}")

        reconverged: dict[str, dict] = {}
        if flavor == "recovery":
            # NOW the evidence is fresh: the controller must confirm
            # and evacuate the genuinely dead node...
            deadline = time.monotonic() + 30.0
            evacuated = False
            while time.monotonic() < deadline and not evacuated:
                evacuated = NODE_B in \
                    self.app.recovery.check_once()["evacuated"]
                if not evacuated:
                    time.sleep(0.05)
            if not evacuated:
                raise InvariantViolation(
                    f"{NODE_B} never evacuated after the api healed: "
                    f"{self.app.recovery.payload()}")
            self.record(f"post-heal evacuation of {NODE_B}")
            # ...and the stranded intent re-converges once rescheduled.
            self.cluster.kube.delete_pod("default", "ao-b")
            self.add_pod("ao-b", NODE_A)
            self.app.elastic.store.put("default", "ao-b", Intent(
                desired_chips=desired_by_pod["ao-b"], min_chips=1))
        if mid is not None:
            self._drive_to_terminal(mid)
            j = self.app.migrations.get(mid) or {}
            if not j.get("outcome"):
                raise InvariantViolation(
                    f"migration {mid} never went terminal after the "
                    f"heal: {j}")
            self.record(f"migration {mid} -> {j.get('outcome')}")
        for node, service in self.services.items():
            if node in self.dead_nodes or service.ledger is None:
                continue
            service.retry_pending_releases()
        self.converge()
        # Final drain: a write enqueued while the first flush was
        # mid-pass (order-preservation rerouting) must not be left
        # pending at judgment time. Idempotent when already empty.
        if replay_enabled:
            store.flush_writes()

        # --- invariant 14: post-heal agreement ---
        violations: list[str] = []
        from gpumounter_tpu.k8s.types import Pod as PodView
        annotations_a = PodView(
            kube_raw.get_pod("default", "ao-a")).annotations
        if replay_enabled:
            if store.queue.pending_count():
                violations.append(
                    f"write-behind queue not drained after heal: "
                    f"{store.queue.stats()}")
            for annot, payload in queued_annotations.items():
                if annotations_a.get(annot) != payload:
                    violations.append(
                        f"queued write {annot} did not land exactly "
                        f"once: {annotations_a.get(annot)!r} != "
                        f"{payload!r}")
            cas_raw = annotations_a.get(
                "tpumounter.io/outage-cas", "{}")
            if jsonlib.loads(cas_raw).get("seq") != 5:
                violations.append(
                    f"CAS replay rolled a newer write backward: "
                    f"{cas_raw}")
            if self.app.apihealth.state() != "healthy":
                violations.append(
                    f"api health stuck {self.app.apihealth.state()} "
                    f"after heal")
            for node, service in self.services.items():
                if node in self.dead_nodes or service.ledger is None:
                    continue
                if service.ledger.pending_releases():
                    violations.append(
                        f"deferred slave release never completed on "
                        f"{node}: {service.ledger.pending_releases()}")
        else:
            missing = [a for a in queued_annotations
                       if a not in annotations_a]
            if missing or store.queue.pending_count():
                raise InvariantViolation(
                    f"write-behind divergence detected (replay "
                    f"disabled): {missing} never landed, "
                    f"{store.queue.pending_count()} write(s) stranded "
                    f"in the queue (seed={self.seed})")
            raise InvariantViolation(
                "negative control failed: replay was disabled yet no "
                "divergence exists")
        if violations:
            tail = "\n  ".join(self.schedule[-25:])
            raise InvariantViolation(
                f"invariant 14 violated (seed={self.seed}, "
                f"flavor={flavor}):\n- " + "\n- ".join(violations)
                + f"\nschedule tail:\n  {tail}")
        # Books == mounts == ledger == intents (the shared closers).
        self.check_invariants()
        return {"flavor": flavor, "flush": flush,
                "apihealth": self.app.apihealth.payload(),
                "migration": mid,
                "reconverged": reconverged,
                "queue": store.queue.stats()}

    def _drive_to_terminal(self, mid: str, timeout_s: float = 30.0) -> None:
        """Wait out the machine; re-adopt after simulated master crashes
        (failpoints cleared first — the 'restarted master' is clean)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            journal = self.app.migrations.wait(mid, timeout_s=5.0)
            if journal is not None and journal.get("outcome"):
                return
            failpoints.disarm_all()
            adopted = self.app.migrations.resume_interrupted()
            if adopted:
                self.record(f"resumed interrupted: {adopted}")

    # --- convergence + invariants ---

    def converge(self, timeout_s: float = 30.0) -> None:
        """Disarm everything, finish interrupted migrations, and drive
        every declared intent to a converged outcome."""
        failpoints.disarm_all()
        deadline = time.monotonic() + timeout_s
        # 1. migrations must all be terminal
        while time.monotonic() < deadline:
            pending = [j for j in self.app.migrations.list_migrations()
                       if not j.get("outcome")]
            if not pending:
                break
            self.app.migrations.resume_interrupted()
            for j in pending:
                self.app.migrations.wait(j["id"], timeout_s=5.0)
        # 2. every intent reconciles clean
        try:
            intents = self.app.elastic.store.list()
        except Exception:  # noqa: BLE001
            intents = []
        for namespace, pod_name, _intent in intents:
            if self.pods.get((namespace, pod_name)) in self.dead_nodes:
                continue  # stranded on a killed node: the node-kill
                # scenario reschedules + re-converges these explicitly
            while time.monotonic() < deadline:
                try:
                    outcome = self.app.elastic.reconcile_once(namespace,
                                                              pod_name)
                except Exception as exc:  # noqa: BLE001 — keep driving
                    self.record(f"converge {pod_name}: retrying ({exc})")
                    time.sleep(0.05)
                    continue
                if outcome.get("phase") in ("converged", "unmanaged",
                                            "gone", "invalid"):
                    break
                time.sleep(0.05)

    def held_chips(self) -> dict[tuple[str, str], set[str]]:
        """(namespace, pod) -> uuids whose device node is present in the
        pod's container /dev — what the tenant can actually touch."""
        held: dict[tuple[str, str], set[str]] = {}
        for (namespace, name), node in self.pods.items():
            dev_dir = os.path.join(self.root, f"container-dev-{node}",
                                   f"{namespace}-{name}")
            chips = set()
            for dev in self.cluster.node(node).backend.list_devices():
                if os.path.exists(os.path.join(dev_dir, dev.rel_path)):
                    chips.add(dev.uuid)
            held[(namespace, name)] = chips
        return held

    def booked_chips(self) -> dict[tuple[str, str], set[str]]:
        """(namespace, pod) -> uuids the scheduler's books say the pod
        owns (device-plugin claims, slave pods included)."""
        booked: dict[tuple[str, str], set[str]] = {}
        for (namespace, name), node in self.pods.items():
            service = self.services[node]
            try:
                pod = Pod(self.cluster.kube.get_pod(namespace, name))
            except NotFoundError:
                booked[(namespace, name)] = set()
                continue
            service.collector.update_status()
            slaves = {s.name for s in
                      service.allocator.slave_pods_for(pod)}
            devices = service.collector.get_pod_devices(
                name, namespace, slave_pod_names=slaves, refresh=False)
            booked[(namespace, name)] = {d.uuid for d in devices}
        return booked

    def check_invariants(self) -> None:
        violations: list[str] = []
        held = self.held_chips()
        booked = self.booked_chips()

        # 1. no chip held by two pods. Chip identity is (node, uuid): the
        # fake backend reuses uuids across nodes, exactly like two hosts
        # each having their own /dev/accel0.
        owners: dict[tuple[str, str], list[tuple[str, str]]] = {}
        for key, chips in held.items():
            node = self.pods[key]
            for uuid in chips:
                owners.setdefault((node, uuid), []).append(key)
        for (node, uuid), holders in owners.items():
            if len(holders) > 1:
                violations.append(
                    f"double-hold: chip {uuid} on {node} mounted in "
                    f"{[f'{ns}/{p}' for ns, p in holders]}")

        for key in self.pods:
            namespace, name = key
            # 2. no ownerless grant
            leaked = held[key] - booked[key]
            if leaked:
                violations.append(
                    f"ownerless grant: {namespace}/{name} has injected "
                    f"node(s) {sorted(leaked)} with no scheduler booking")
            # 3. accounting parity
            phantom = booked[key] - held[key]
            if phantom:
                violations.append(
                    f"accounting mismatch: {namespace}/{name} books "
                    f"{sorted(phantom)} but the node(s) are not mounted")

        # 4. every migration journal terminal
        journals = self.app.migrations.list_migrations()
        for journal in journals:
            outcome = journal.get("outcome")
            if outcome not in ("succeeded", "rolled-back", "aborted") or \
                    journal.get("phase") != "done":
                violations.append(
                    f"journal {journal.get('id')} not terminal/clean: "
                    f"phase={journal.get('phase')} outcome={outcome} "
                    f"error={journal.get('error')}")

        # 5. no orphan open spans: every span entered was exited, even
        # through injected crashes (the exporter's finally discipline).
        orphans = trace.TRACER.open_spans()
        if orphans:
            violations.append(f"orphan open span(s): {orphans}")

        # 6. terminal audit records. Every terminal journal must appear
        # in the audit trail (crashed-and-resumed machines included),
        # and no record may be outcome-less or trace-less.
        audit_records = AUDIT.snapshot()
        migrate_ids = {r.get("details", {}).get("id")
                       for r in audit_records
                       if r["operation"] == "migrate"}
        for journal in journals:
            if journal.get("outcome") and journal["id"] not in migrate_ids:
                violations.append(
                    f"migration {journal['id']} finished "
                    f"({journal['outcome']}) but left no terminal audit "
                    f"record")
        for rec in audit_records:
            if not rec.get("outcome"):
                violations.append(
                    f"audit record without outcome: seq={rec['seq']} "
                    f"op={rec['operation']} pod={rec['pod']}")
            if not rec.get("trace_id"):
                violations.append(
                    f"audit record without trace id: seq={rec['seq']} "
                    f"op={rec['operation']} pod={rec['pod']}")

        # 8. fleet rollups never double-count a node across collector
        # restarts: the rollup is node-keyed and workers report absolute
        # counters, so a restarted collector (second fresh instance)
        # must reproduce the first one's numbers exactly.
        from gpumounter_tpu.obs.fleet import FleetCollector
        rollups = []
        for _ in range(2):  # second construction = "restarted collector"
            collector = FleetCollector(self.app.registry,
                                       self.app._client_factory,
                                       cfg=self.cfg)
            rollups.append(collector.collect_once())
        first, second = rollups
        expected_nodes = set(self.services) - self.dead_nodes
        for which, rollup in (("first", first), ("second", second)):
            if set(rollup["nodes"]) - self.dead_nodes != expected_nodes:
                violations.append(
                    f"fleet rollup ({which}) nodes "
                    f"{sorted(rollup['nodes'])} != workers "
                    f"{sorted(expected_nodes)}")
            node_sum = sum(e.get("mount", {}).get("count", 0)
                           for e in rollup["nodes"].values())
            if rollup["fleet"]["mount_count"] != node_sum:
                violations.append(
                    f"fleet rollup ({which}) total "
                    f"{rollup['fleet']['mount_count']} != per-node sum "
                    f"{node_sum} (a node counted twice or dropped)")
        for node in expected_nodes & set(first["nodes"]) \
                & set(second["nodes"]):
            a = first["nodes"][node].get("mount", {}).get("count", 0)
            b = second["nodes"][node].get("mount", {}).get("count", 0)
            if a != b:
                violations.append(
                    f"collector restart changed node {node} mount count "
                    f"{a} -> {b} (rollup not restart-stable)")

        # 17. capacity-plane agreement: the collected /capacity
        # inventory (same pass as invariant 8's first rollup) must
        # equal the fake scheduler's ground truth chip-for-chip —
        # free indices, held+warm count, fenced indices. A withheld
        # unmount (the negative control erases a kubelet claim without
        # unmounting) reads as divergence here.
        for node in sorted(expected_nodes & set(first["nodes"])):
            cap = first["nodes"][node].get("capacity")
            if not isinstance(cap, dict):
                violations.append(
                    f"capacity divergence on {node}: node reported no "
                    f"capacity section")
                continue
            fake = self.cluster.node(node)
            with self.cluster._alloc_lock:
                free_truth = sorted(int(c) for c in fake.free_ids())
                held_truth = sorted(
                    int(c) for c, owner in fake.assignment.items()
                    if owner is not None and c not in fake.dead)
                fenced_truth = sorted(int(c) for c in fake.dead)
            free_rep = sorted(int(i) for i in cap.get("free") or [])
            warm_rep = sorted(int(i) for i in cap.get("warm") or [])
            held_rep = sorted(int(i) for i in cap.get("held") or {})
            fenced_rep = sorted(int(i) for i in cap.get("fenced") or [])
            if free_rep != free_truth:
                violations.append(
                    f"capacity divergence on {node}: reported free "
                    f"{free_rep} != ground truth {free_truth}")
            if sorted(held_rep + warm_rep) != held_truth:
                violations.append(
                    f"capacity divergence on {node}: reported "
                    f"held+warm {sorted(held_rep + warm_rep)} != "
                    f"ground-truth bookings {held_truth}")
            if fenced_rep != fenced_truth:
                violations.append(
                    f"capacity divergence on {node}: reported fenced "
                    f"{fenced_rep} != ground-truth dead {fenced_truth}")

        # 10. ledger agreement (armed by run_worker_crash_scenario):
        # after crash+restart+replay at any failpoint, every node's
        # ledger has no open transactions and its net holdings equal
        # both the injected nodes and the scheduler's bookings.
        if self.check_ledgers:
            for node, service in self.services.items():
                if node in self.dead_nodes or service.ledger is None:
                    continue
                open_txns = service.ledger.open_transactions()
                if open_txns:
                    violations.append(
                        f"ledger on {node} left open txn(s) after "
                        f"convergence: {[t['txn'] for t in open_txns]}")
                holdings = service.ledger.net_holdings()
                for key, node_of in self.pods.items():
                    if node_of != node:
                        continue
                    ledger_view = holdings.get(key, set())
                    if ledger_view != held[key]:
                        violations.append(
                            f"ledger/mounts disagree for "
                            f"{key[0]}/{key[1]} on {node}: ledger "
                            f"{sorted(ledger_view)} != mounted "
                            f"{sorted(held[key])}")
                    if ledger_view != booked[key]:
                        violations.append(
                            f"ledger/books disagree for "
                            f"{key[0]}/{key[1]} on {node}: ledger "
                            f"{sorted(ledger_view)} != booked "
                            f"{sorted(booked[key])}")

        # 13. tenant disruption closure (armed by attach_tenant): after
        # terminal migrations/heals/evacuations no window is open, and
        # every signalled-cause window carries a trace id that resolves
        # in the trace ring — attributable downtime, never a mystery.
        if self.tenant_sims:
            from gpumounter_tpu.jaxside.telemetry import SIGNALLED_CAUSES
            for sim in self.tenant_sims.values():
                sim.settle()
                snap = sim.telemetry.snapshot()
                tenant = sim.telemetry.tenant
                for window in snap["disruption"]["open"]:
                    violations.append(
                        f"tenant {tenant}: disruption window left open "
                        f"after convergence: {window}")
                for window in snap["disruption"]["windows"]:
                    if window["cause"] not in SIGNALLED_CAUSES:
                        continue
                    if not window["trace_id"]:
                        violations.append(
                            f"tenant {tenant}: {window['cause']} window "
                            f"without a control-plane trace id "
                            f"(unattributed downtime): {window}")
                    elif trace.trace_payload(window["trace_id"]) is None:
                        violations.append(
                            f"tenant {tenant}: {window['cause']} window "
                            f"trace {window['trace_id']} does not "
                            f"resolve in the trace ring")

        # 16. trace-assembly closure: every clean mount/remove op the
        # harness drove (chaos.<op> root span, no fault armed, ended
        # ok) must assemble completely — no orphan spans, no
        # successful rpc.* span missing its worker half — and the
        # critical path's per-phase attribution must sum to the edge
        # span's wall time. A dropped worker span ring (the negative
        # control drives exactly that) reads as incomplete here.
        from gpumounter_tpu.obs import assembly
        for op in self.traced_ops:
            tree = assembly.assemble(op["trace"])
            if tree is None:
                violations.append(
                    f"traced op {op['op']!r} (trace {op['trace']}) "
                    f"expired from the span stores before assembly")
                continue
            if not tree["complete"]:
                violations.append(
                    f"traced op {op['op']!r} (trace {op['trace']}) "
                    f"assembles INCOMPLETE: {len(tree['orphans'])} "
                    f"orphan span(s) {tree['orphans']}, "
                    f"{len(tree['missing_worker_halves'])} rpc span(s) "
                    f"missing their worker half")
                continue
            phase_sum = sum(tree["phases"].values())
            wall = tree["wall_ms"]
            if abs(phase_sum - wall) > max(2.0, 0.05 * wall):
                violations.append(
                    f"traced op {op['op']!r} (trace {op['trace']}): "
                    f"critical-path phase sum {phase_sum:.3f}ms != "
                    f"edge wall {wall:.3f}ms")

        # 18. defrag closure (armed by run_defrag_scenario): after a
        # defrag run the fleet fragmentation index sampled at the
        # plan's barrier points must be monotonically non-increasing (a
        # "defragmenter" that fragments is worse than none), every
        # executed move must have succeeded with its migration journal
        # terminal (invariant 4 re-checks cleanliness), and every
        # move's disruption window must be trace-attributed: the
        # assembled trace carries migrate-phase wall time. Books ==
        # mounts == ledger == capacity over the same run are invariants
        # 1-3, 10 and 17.
        for run in self.defrag_runs:
            samples = [b["fragmentation_index"]
                       for b in run.get("barriers", [])
                       if "fragmentation_index" in b]
            for earlier, later in zip(samples, samples[1:]):
                if later > earlier + 1e-9:
                    violations.append(
                        f"defrag {run.get('plan_id')}: fragmentation "
                        f"index rose across a barrier point "
                        f"({earlier} -> {later}; samples {samples})")
            if run.get("status") != "completed":
                violations.append(
                    f"defrag {run.get('plan_id')} did not complete: "
                    f"{run.get('status')!r} ({run.get('error')})")
            for move in run.get("moves", []):
                who = f"{move.get('namespace')}/{move.get('pod')}"
                if move.get("outcome") != "succeeded":
                    violations.append(
                        f"defrag {run.get('plan_id')}: move of {who} "
                        f"-> {move.get('dest_node')} ended "
                        f"{move.get('outcome')!r}")
                    continue
                tree = assembly.assemble(move.get("trace_id") or "")
                if tree is None:
                    violations.append(
                        f"defrag {run.get('plan_id')}: move of {who} "
                        f"(trace {move.get('trace_id')}) does not "
                        f"assemble — unattributed tenant window")
                elif not tree["phases"].get("migrate"):
                    violations.append(
                        f"defrag {run.get('plan_id')}: move of {who} "
                        f"(trace {move.get('trace_id')}) assembled "
                        f"without migrate-phase wall time: "
                        f"{tree['phases']}")

        # 19. fractional-share agreement (armed by run_share_scenario):
        # after convergence the three share ledgers agree chip-for-chip
        # and value-for-value — master share books == policy entries
        # (the userspace engine standing in for the kernel map on fake
        # backends) == worker ledger share records. Weights must be
        # equal; metered-ness must be equal in kind (the engine's
        # REMAINING tokens may legitimately sit below the booked
        # budget — they are consumed — but an unmetered book entry must
        # never be metered in the map or vice versa). Then the throttle
        # decision procedure itself is proven: a metered share refilled
        # to k tokens admits exactly k accesses and then denies,
        # identically through the engine and through the interpreter
        # executing the real in-kernel program bytecode, with matching
        # post-state. The negative control (disable_enforcement) admits
        # past exhaustion and reads as decision divergence here.
        if self.vchip_armed:
            from gpumounter_tpu.cgroup import ebpf as ebpf_mod
            from gpumounter_tpu.cgroup.policy import POLICY_ENGINE
            books = self.app.shares.books()
            for scope in POLICY_ENGINE.scopes():
                if scope not in books:
                    violations.append(
                        f"policy engine scope {scope!r} has entries but "
                        f"no master share books (leaked policy)")
            for (ns, name), node in sorted(self.pods.items()):
                tenant = f"{ns}/{name}"
                if node in self.dead_nodes:
                    continue
                want = books.get(tenant, {})
                service = self.services[node]
                ledger_shares = {}
                if service.ledger is not None:
                    ledger_shares = service.ledger.share_holdings().get(
                        (ns, name), {})
                # Books <-> ledger: chip-exact, value-exact.
                if set(want) != set(ledger_shares):
                    violations.append(
                        f"share books/ledger diverge for {tenant}: "
                        f"books {sorted(want)} != ledger "
                        f"{sorted(ledger_shares)}")
                else:
                    for uuid, (weight, budget) in sorted(want.items()):
                        if ledger_shares[uuid] != (weight, budget):
                            violations.append(
                                f"ledger share record diverges for "
                                f"{tenant} chip {uuid}: "
                                f"{ledger_shares[uuid]} != books "
                                f"({weight}, {budget})")
                # Books <-> policy entries: at the map's REAL
                # granularity, (major, minor) keys — the fake backend
                # mknods every chip from the same device numbers, so
                # distinct chips legitimately project onto one key
                # (exactly what the kernel map would hold there too).
                devs_by_uuid = {
                    d.uuid: d for d in
                    self.cluster.node(node).backend.list_devices()}
                expected: dict[int, set[tuple[int, bool]]] = {}
                for uuid, (weight, budget) in want.items():
                    dev = devs_by_uuid.get(uuid)
                    if dev is None:
                        violations.append(
                            f"booked share chip {uuid} for {tenant} "
                            f"is not a device {node} has")
                        continue
                    expected.setdefault(
                        ebpf_mod.telemetry_key(dev.major, dev.minor),
                        set()).add((weight, budget > 0))
                entries = POLICY_ENGINE.entries(tenant)
                if set(entries) != set(expected):
                    violations.append(
                        f"share policy keys diverge for {tenant}: "
                        f"books project to "
                        f"{sorted(hex(k) for k in expected)} != policy "
                        f"entries {sorted(hex(k) for k in entries)}")
                    continue
                for key, value in sorted(entries.items()):
                    got = (ebpf_mod.policy_weight(value),
                           ebpf_mod.policy_tokens(value)
                           != ebpf_mod.POLICY_UNMETERED)
                    if got not in expected[key]:
                        violations.append(
                            f"share policy value diverges for {tenant} "
                            f"key {key:#x}: entry (weight, metered) "
                            f"{got} not among booked {expected[key]}")
            violations.extend(self._throttle_agreement(books))

        # 20. gray-failure attribution closure (armed by
        # run_gray_scenario): every automatic quarantine the health
        # plane committed is flight-recorded with at least one concrete
        # scoring signal, no node outside the deliberately degraded set
        # was ever quarantined, and every degraded node ended
        # quarantined — a disabled scorer (the negative control) reads
        # as a missed detection here.
        if self.gray_armed:
            from gpumounter_tpu.obs.flight import FLIGHT
            panes = self.app.health.payload()["nodes"]
            quarantined_now = {
                n for n, p in panes.items()
                if p["state"] == "quarantined" and not p["evacuated"]}
            for rec in FLIGHT.snapshot():
                if rec.get("kind") != "health":
                    continue
                det = rec.get("details") or {}
                if det.get("to_state") != "quarantined" \
                        or det.get("from_state") == "quarantined":
                    continue
                node = rec.get("node")
                if not det.get("signals"):
                    violations.append(
                        f"quarantine of {node} carries no concrete "
                        f"signal in the flight record (unattributed "
                        f"quarantine): {rec.get('summary')}")
                if node not in self.gray_nodes:
                    violations.append(
                        f"false quarantine: {node} was quarantined but "
                        f"no gray fault was armed on it "
                        f"(signals: {det.get('signals')})")
            if quarantined_now - self.gray_nodes:
                violations.append(
                    f"false quarantine set: "
                    f"{sorted(quarantined_now - self.gray_nodes)} "
                    f"quarantined without an armed gray fault")
            for node in sorted(self.gray_nodes):
                if node not in quarantined_now:
                    violations.append(
                        f"gray failure NOT detected: {node} limped "
                        f"through the whole scenario but ended "
                        f"{panes.get(node, {}).get('state', 'untracked')!r}"
                        f" instead of quarantined")

        # 21. autoscale decision closure (armed by
        # run_autoscale_scenario): every fired decision is
        # trace-attributed with a matching audit record, none fired
        # through a recorded-closed gate, and after convergence every
        # autoscale tenant's mounted chips equal its declared intent —
        # the autoscaler's writes are exactly as durable and exactly as
        # converged as an operator's own intent edits.
        if self.autoscale_armed:
            audit_by_trace: dict[str, list[dict]] = {}
            for rec in AUDIT.snapshot():
                if rec["operation"] == "autoscale.decision":
                    audit_by_trace.setdefault(
                        rec.get("trace_id") or "", []).append(rec)
            for record in self.autoscale_passes:
                for decision in record.get("decisions", []):
                    if decision["action"] not in ("grow", "shrink"):
                        continue
                    who = decision["tenant"]
                    gates = decision.get("gates") or {}
                    if gates.get("paused") or not gates.get("api_ok") \
                            or gates.get("slo_burning"):
                        violations.append(
                            f"autoscale {decision['action']} of {who} "
                            f"fired through a closed gate: {gates}")
                    trace_id = decision.get("trace_id")
                    if not trace_id:
                        violations.append(
                            f"autoscale {decision['action']} of {who} "
                            f"carries no trace id (unattributable "
                            f"decision)")
                        continue
                    matches = [
                        r for r in audit_by_trace.get(trace_id, [])
                        if r["pod"] == decision["pod"]
                        and r.get("details", {}).get("action")
                        == decision["action"]]
                    if not matches:
                        violations.append(
                            f"autoscale {decision['action']} of {who} "
                            f"(trace {trace_id}) left no matching "
                            f"autoscale.decision audit record")
            for ns, name in self.autoscale_pods:
                if self.pods.get((ns, name)) in self.dead_nodes:
                    continue
                intent = self.app.elastic.store.get(ns, name)
                if intent is None:
                    violations.append(
                        f"autoscale tenant {ns}/{name} lost its intent")
                    continue
                mounted = len(self.probe(ns, name))
                if mounted != intent.desired_chips:
                    violations.append(
                        f"autoscale tenant {ns}/{name} diverged: "
                        f"intent desires {intent.desired_chips} "
                        f"chip(s) but {mounted} are mounted after "
                        f"convergence")

        # 22. watch-store index parity (armed by
        # run_watch_store_scenario): after severed watches, 410 storms
        # and a master restart, the informer's in-memory indexes —
        # worker pods, intents, journals, per-node pool buckets — must
        # agree EXACTLY with a fresh list-backed view of the same
        # cluster. The comparison polls briefly (the stream is
        # eventually consistent by design) but a divergence that
        # outlives the deadline is a lost/phantom entry: a poisoned
        # index (the negative control) reads as exactly that.
        if self.watchstore_armed:
            deadline = time.monotonic() + 4.0
            while True:
                self.watch_store.quiesce(1.0)
                watch_diverged = self._watch_parity()
                if not watch_diverged or time.monotonic() > deadline:
                    break
            violations.extend(watch_diverged)

        # 7. no leaked channels: exact pool accounting under chaos.
        stats = self.channel_pool.stats()
        if stats["dialed"] != stats["live"] + stats["closed"]:
            violations.append(
                f"channel-pool books off: dialed={stats['dialed']} != "
                f"live={stats['live']} + closed={stats['closed']}")
        if stats["live"] > len(self._port_by_ip):
            violations.append(
                f"channel leak: {stats['live']} live channel(s) for "
                f"{len(self._port_by_ip)} worker(s)")

        # 15. lock-order consistency: every nested OrderedLock
        # acquisition the whole run observed (instrumented modules:
        # metrics, fake apiserver, migration machine, tracer, worker
        # ledger) must form an acyclic order. The static half of the
        # check lives in tools/tpulint (lockorder.py); TPM_LOCK_TRACE
        # exports what we validated so the static-analysis lane can
        # cross-check runtime reality against the reviewed graph
        # (python -m tools.tpulint --verify-dynamic <file>).
        try:
            locks.RECORDER.assert_consistent()
        except locks.LockOrderViolation as exc:
            violations.append(f"lock-order: {exc}")
        trace_path = os.environ.get("TPM_LOCK_TRACE", "")  # tpulint: allow[env-through-config] CI-artifact path for the test harness, not a daemon runtime knob
        if trace_path:
            import json as _json
            with open(trace_path, "w", encoding="utf-8") as f:
                _json.dump(locks.RECORDER.dump(), f, indent=1)

        if violations:
            tail = "\n  ".join(self.schedule[-25:])
            raise InvariantViolation(
                f"chaos invariants violated (seed={self.seed}):\n- "
                + "\n- ".join(violations)
                + f"\nschedule tail:\n  {tail}")

    def _watch_parity(self) -> list[str]:
        """Invariant 22's comparison: every watch-store index against a
        fresh list-backed store reading the same cluster."""
        from gpumounter_tpu.store import KubeMasterStore
        out: list[str] = []
        store = self.watch_store
        cfg = self._watch_cfg
        ref = KubeMasterStore(self.cluster.kube, cfg)

        def _names(pods):
            return sorted((p["metadata"]["namespace"],
                           p["metadata"]["name"]) for p in pods)

        got = _names(store.list_worker_pods())
        want = _names(ref.list_worker_pods())
        if got != want:
            out.append(f"invariant 22: worker index diverges from a "
                       f"fresh LIST: indexed {got} != listed {want}")
        by_pod = lambda t: (t[0], t[1])  # noqa: E731
        got_i = sorted(store.list_intents(), key=by_pod)
        want_i = sorted(ref.list_intents(), key=by_pod)
        if got_i != want_i:
            out.append(f"invariant 22: intent index diverges from a "
                       f"fresh LIST: indexed {got_i} != listed {want_i}")
        got_j = sorted(store.scan_journals(), key=lambda j: j["id"])
        want_j = sorted(ref.scan_journals(), key=lambda j: j["id"])
        if got_j != want_j:
            out.append(f"invariant 22: journal index diverges from a "
                       f"fresh LIST: indexed "
                       f"{[j['id'] for j in got_j]} != listed "
                       f"{[j['id'] for j in want_j]}")
        nodes = {Pod(p).node_name
                 for p in self.cluster.kube.list_pods(cfg.pool_namespace)
                 if Pod(p).node_name}
        with store._mu:
            nodes |= set(store._pool_by_node)
        for node in sorted(nodes):
            got_p = sorted(p["metadata"]["name"]
                           for p in store.list_pool_pods(node))
            want_p = sorted(p["metadata"]["name"]
                            for p in ref.list_pool_pods(node))
            if got_p != want_p:
                out.append(f"invariant 22: pool bucket for {node} "
                           f"diverges from a fresh LIST: indexed "
                           f"{got_p} != listed {want_p}")
        return out

    def _throttle_agreement(self, books: dict) -> list[str]:
        """Invariant 19's decision-parity half: drive one metered share
        past a refilled k-token budget through BOTH deciders — the
        userspace engine and the interpreter executing the real program
        bytecode over dict-backed maps — and report any access where
        they disagree, any access past the budget that is NOT denied,
        and any remaining-token post-state mismatch. Repeatable: the
        probe refills the engine entry to k tokens before driving, so a
        second check_invariants() call reproduces the same walk."""
        from gpumounter_tpu.cgroup import ebpf as ebpf_mod
        from gpumounter_tpu.cgroup.policy import (
            POLICY_ENGINE, interpret_device_program)
        target = next(
            ((tenant, uuid, weight, budget)
             for tenant, shares in sorted(books.items())
             for uuid, (weight, budget) in sorted(shares.items())
             if budget > 0), None)
        if target is None:
            return ["share scenario converged with no metered share "
                    "left to probe throttling"]
        tenant, uuid, weight, _budget = target
        ns, name = tenant.split("/", 1)
        node = self.pods[(ns, name)]
        dev = next(d for d in
                   self.cluster.node(node).backend.list_devices()
                   if d.uuid == uuid)
        probe_tokens = 3
        POLICY_ENGINE.refill(tenant, dev.major, dev.minor, probe_tokens)
        key = ebpf_mod.telemetry_key(dev.major, dev.minor)
        tmap_fd, pmap_fd = 5, 7
        prog = ebpf_mod.build_device_program(
            (), telemetry_map_fd=tmap_fd, policy_map_fd=pmap_fd)
        maps = {tmap_fd: {key: 0},
                pmap_fd: {key: ebpf_mod.policy_value(weight,
                                                     probe_tokens)}}
        rw = ebpf_mod.BPF_DEVCG_ACC_READ | ebpf_mod.BPF_DEVCG_ACC_WRITE
        out: list[str] = []
        for step in range(1, probe_tokens + 3):
            engine = POLICY_ENGINE.admit(tenant, dev.major, dev.minor)
            kernel = bool(interpret_device_program(
                prog, maps, ebpf_mod.BPF_DEVCG_DEV_CHAR, rw,
                dev.major, dev.minor))
            if bool(engine) != kernel:
                out.append(
                    f"throttle divergence for {tenant} chip {uuid} at "
                    f"access {step} of a {probe_tokens}-token budget: "
                    f"engine admits={engine} != in-kernel program "
                    f"admits={kernel}")
            if step > probe_tokens and kernel:
                out.append(
                    f"tenant {tenant} chip {uuid} NOT throttled "
                    f"in-kernel past its {probe_tokens}-token budget "
                    f"(access {step} admitted)")
        left_engine = ebpf_mod.policy_tokens(
            POLICY_ENGINE.entries(tenant).get(key, 0))
        left_kernel = ebpf_mod.policy_tokens(maps[pmap_fd][key])
        if left_engine != left_kernel:
            out.append(
                f"throttle post-state diverges for {tenant} chip "
                f"{uuid}: engine tokens left {left_engine} != map "
                f"tokens left {left_kernel}")
        return out


# --- invariant 12: stale-shard partition -> fencing (run standalone) ---

def run_fencing_scenario(seed: int, n_stale_ops: int = 6) -> list[str]:
    """Seeded stale-shard-partition chaos: a real worker (ledger on),
    an old shard owner that keeps acting after losing its lease, and
    the new owner's live traffic. Invariant: NO stale-epoch write is
    ever applied — every ghost mutation raises FencedError and provably
    changes nothing (bookings and mounted-node sets are compared around
    each attempt), while the new owner's same-shaped traffic lands.
    Raises InvariantViolation with the executed schedule on any breach.
    """
    import random as random_mod
    import tempfile

    from gpumounter_tpu.config import Config
    from gpumounter_tpu.master.shard import ShardManager
    from gpumounter_tpu.rpc.resilience import FencedError

    rng = random_mod.Random(seed)
    schedule: list[str] = []

    def record(event: str) -> None:
        schedule.append(event)
        logger.info("fencing-chaos[seed=%d] %s", seed, event)

    def fail(message: str) -> None:
        raise InvariantViolation(
            f"invariant 12 violated (seed={seed}): {message}\n"
            f"schedule:\n  " + "\n  ".join(schedule[-25:]))

    with tempfile.TemporaryDirectory() as root:
        cluster = FakeCluster(os.path.join(root, "cluster"),
                              n_chips=6).start()
        try:
            node_cfg = cluster.node_cfg(cluster.node_name).replace(
                ledger_dir=os.path.join(root, "ledger"))
            collector = TpuCollector(
                backend=cluster.backend,
                podresources=PodResourcesClient(
                    cluster.cfg.kubelet_socket, timeout_s=5.0),
                cfg=node_cfg)
            mounter = TpuMounter(cluster.backend, cfg=node_cfg)
            dev_dir = os.path.join(root, "container-dev")
            os.makedirs(dev_dir, exist_ok=True)
            mounter.resolve_target = lambda pod: MountTarget(
                dev_dir=dev_dir,
                description=f"{pod.namespace}/{pod.name}", pod=pod)
            service = TpuMountService(cluster.kube, collector=collector,
                                      mounter=mounter, cfg=node_cfg)
            server = build_server(service, address="localhost:0")
            server.start()
            address = f"localhost:{server.bound_port}"
            cluster.add_target_pod("tenant")

            lease_cfg = Config().replace(shard_count=1,
                                         shard_lease_duration_s=0.3,
                                         shard_preferred="")
            node = cluster.node_name
            old_owner = ShardManager(cluster.kube, cfg=lease_cfg,
                                     replica_id="ghost",
                                     advertise_url="http://ghost",
                                     preferred=None).start_without_loop()
            old_owner.acquire_once()
            stale_epoch = old_owner.node_epoch(node)
            if stale_epoch <= 0:
                fail("old owner acquired no epoch")
            with WorkerClient(address, cfg=node_cfg) as client:
                result = client.add_tpu("tenant", "default", 1,
                                        epoch=stale_epoch)
                record(f"old owner mounted 1 chip at epoch "
                       f"{stale_epoch} -> {result.name}")

                # Partition: the ghost stops renewing but keeps acting.
                # A new replica takes the lease over after expiry.
                new_owner = ShardManager(
                    cluster.kube, cfg=lease_cfg, replica_id="successor",
                    advertise_url="http://successor",
                    preferred=None).start_without_loop()
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline \
                        and not new_owner.owned_shards():
                    new_owner.acquire_once()
                    time.sleep(0.05)
                fresh_epoch = new_owner.node_epoch(node)
                if fresh_epoch <= stale_epoch:
                    fail(f"takeover epoch {fresh_epoch} not newer than "
                         f"{stale_epoch}")
                record(f"takeover: successor owns at epoch {fresh_epoch}")
                # The new owner touches the node once: the worker now
                # remembers the fresh epoch durably.
                result = client.add_tpu("tenant", "default", 1,
                                        epoch=fresh_epoch)
                record(f"new owner mounted at epoch {fresh_epoch} "
                       f"-> {result.name}")

                def state() -> tuple[int, tuple[str, ...]]:
                    return (cluster.free_chip_count(),
                            tuple(sorted(os.listdir(dev_dir))))

                for op_index in range(n_stale_ops):
                    before = state()
                    kind = rng.choice(["add", "remove"])
                    try:
                        if kind == "add":
                            client.add_tpu("tenant", "default",
                                           rng.randint(1, 2),
                                           epoch=stale_epoch)
                        else:
                            client.remove_tpu("tenant", "default", [],
                                              remove_all=True, force=True,
                                              epoch=stale_epoch)
                        fail(f"stale {kind} (epoch {stale_epoch}) was "
                             f"APPLIED at op {op_index}")
                    except FencedError:
                        record(f"stale {kind} -> FENCED (op {op_index})")
                    if state() != before:
                        fail(f"stale {kind} changed node state at op "
                             f"{op_index}: {before} -> {state()}")
                    if rng.random() < 0.5:
                        # Interleave live-owner traffic: fencing must be
                        # selective, not a node lockdown.
                        result = client.add_tpu("tenant", "default", 1,
                                                epoch=fresh_epoch)
                        record(f"new owner add -> {result.name}")
                if service.ledger.epoch() != fresh_epoch:
                    fail(f"worker persisted epoch "
                         f"{service.ledger.epoch()} != {fresh_epoch}")
            record("fencing held: no stale-epoch write applied")
            server.stop(grace=None)
        finally:
            cluster.stop()
    return schedule


# --- invariant 9: single shard owner per node (master/shard.py) ---

def run_shard_scenario(seed: int, shard_count: int = 5,
                       replicas: int = 3, n_ops: int = 40,
                       lease_duration_s: float = 0.35) -> list[str]:
    """Seeded lease chaos over the fake API server: master replicas
    acquire/renew shard leases while the schedule crashes them (the
    ghost keeps *believing* it owns until self-expiry — the dangerous
    window), restarts them (same identity, fresh process), and lets
    leases expire for takeover. After EVERY step the invariant is
    checked over all views, live and ghost:

      * no shard is claimed by two replica views at once — and since
        the HashRing maps each node to exactly one shard, no node ever
        has two owners;
      * every manager agrees on the node -> shard mapping (ring
        determinism: routing never depends on which replica you ask).

    Convergence: once crashes stop, driving the live managers' renew
    passes must end with every shard owned by exactly one live replica.
    Raises InvariantViolation with the executed schedule on any breach.
    """
    from gpumounter_tpu.config import Config
    from gpumounter_tpu.k8s.fake import FakeKubeClient
    from gpumounter_tpu.master.shard import ShardManager

    rng = random.Random(seed)
    schedule: list[str] = []
    cfg = Config().replace(shard_count=shard_count,
                           shard_lease_duration_s=lease_duration_s,
                           shard_preferred="")
    kube = FakeKubeClient()
    next_instance = iter(range(10_000))

    def new_manager(replica: str) -> ShardManager:
        return ShardManager(
            kube, cfg=cfg, replica_id=replica,
            advertise_url=f"http://{replica}:8080",
            preferred=None).start_without_loop()

    live: dict[str, ShardManager] = {
        f"rep-{i}": new_manager(f"rep-{i}") for i in range(replicas)}
    #: crashed-but-partitioned views: the process is gone from the
    #: schedule's perspective but its last owned_shards() judgment is
    #: exactly what a paused/partitioned master would still act on.
    ghosts: dict[str, ShardManager] = {}
    nodes = [f"storm-node-{j}" for j in range(64)]

    def record(event: str) -> None:
        schedule.append(event)
        logger.info("shard-chaos[seed=%d] %s", seed, event)

    def check(context: str) -> None:
        views = list(live.values()) + list(ghosts.values())
        by_shard: dict[int, list[str]] = {}
        for view in views:
            for s in view.owned_shards():
                by_shard.setdefault(s, []).append(view.replica_id)
        violations = [
            f"shard {s} owned by {sorted(owners)} simultaneously"
            for s, owners in by_shard.items() if len(set(owners)) > 1]
        rings = {tuple(v.ring.owner_of(n) for n in nodes) for v in views}
        if len(rings) > 1:
            violations.append("replicas disagree on node->shard mapping")
        if violations:
            tail = "\n  ".join(schedule[-25:])
            raise InvariantViolation(
                f"invariant 9 violated at {context} (seed={seed}):\n- "
                + "\n- ".join(violations)
                + f"\nschedule tail:\n  {tail}")

    for op_index in range(n_ops):
        roll = rng.random()
        if roll < 0.15 and len(live) > 1:
            victim = rng.choice(sorted(live))
            ghosts[f"{victim}#{next(next_instance)}"] = live.pop(victim)
            record(f"crash {victim} (ghost keeps its claim view)")
        elif roll < 0.30 and ghosts:
            # Restart: the OLD process is truly dead the moment its
            # replacement exists (one pod name runs once), so the ghost
            # view retires and a fresh manager with the same identity
            # re-enters — it may re-claim its own still-held lease.
            ghost_key = rng.choice(sorted(ghosts))
            ghost = ghosts.pop(ghost_key)
            replica = ghost.replica_id
            if replica not in live:
                live[replica] = new_manager(replica)
                record(f"restart {replica} (fresh process, same id)")
        elif roll < 0.45:
            time.sleep(rng.uniform(0.05, lease_duration_s * 1.2))
            record("sleep (leases age toward expiry)")
        else:
            replica = rng.choice(sorted(live))
            newly = live[replica].acquire_once()
            record(f"acquire pass on {replica} -> newly {sorted(newly)}")
        check(f"op {op_index}")

    # Convergence: crashes over; live managers must soak up every shard
    # (expired ghost leases are claimable by anyone), each shard ending
    # with exactly one live owner.
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        for manager in live.values():
            manager.acquire_once()
        check("convergence")
        owned = set()
        for manager in live.values():
            owned |= manager.owned_shards()
        if owned == set(range(shard_count)):
            break
        time.sleep(0.05)
    else:
        raise InvariantViolation(
            f"shards never fully re-owned after chaos (seed={seed}): "
            f"missing {set(range(shard_count)) - owned}\nschedule:\n  "
            + "\n  ".join(schedule[-25:]))
    check("final")
    record(f"converged: all {shard_count} shards owned by "
           f"{sorted(live)}")
    return schedule
