"""Test/bench substrate: simulated single-node TPU cluster.

The reference ships no test infrastructure at all (SURVEY.md §4); this
package is the substrate its survey prescribes: fake device dir + in-process
fake kubelet pod-resources server + fake API server with a device-plugin-
emulating scheduler.
"""

from gpumounter_tpu.testing.cluster import FakeCluster

__all__ = ["FakeCluster"]
