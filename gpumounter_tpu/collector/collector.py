"""TpuCollector: node chip inventory + pod↔chip ownership.

Reference parity: GPUCollector (collector.go:19-163) —
  * NewGPUCollector = enumerate + initial status refresh (collector.go:23-38)
  * UpdateGPUStatus = kubelet pod-resources List → mark owners (collector.go:90-138)
  * GetPodGPUResources = refresh, then devices owned by the pod or its
    slave pods (collector.go:149-163)
  * GetGPUByUUID (collector.go:81-88)

TPU-native deltas (SURVEY.md §7):
  * Enumeration is the device backend (readdir+stat of /dev/accel*), not NVML.
  * Resource name google.com/tpu, pod-resources v1 with v1alpha1 fallback.
  * The reference mutates GPUList with no lock while serving concurrent RPCs
    (SURVEY.md §5 race hazard); all state here is guarded by an RLock.
  * Device-ID matching is tolerant of the plugin's ID scheme: the GKE TPU
    device plugin advertises bare chip indices ("0".."7"); we also accept
    accelN basenames, device paths, and our uuid form.
"""

from __future__ import annotations

import re
import threading

from gpumounter_tpu.collector.podresources import (
    PodResourcesClient,
    iter_device_claims,
)
from gpumounter_tpu.config import get_config
from gpumounter_tpu.device.backend import DeviceBackend, backend_from_config
from gpumounter_tpu.device.tpu import TpuDevice
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("collector")

_INDEX_RE = re.compile(r"^(?:accel)?(\d+)$")


class TpuCollector:
    def __init__(self, backend: DeviceBackend | None = None,
                 podresources: PodResourcesClient | None = None,
                 cfg=None):
        self.cfg = cfg or get_config()
        self.backend = backend or backend_from_config(self.cfg)
        self._podresources = podresources
        self._lock = threading.RLock()
        self.devices: list[TpuDevice] = []
        # False whenever the last pod-resources query failed: the chip list
        # is live but ownership marks are stale/unknown.
        self.ownership_known = False
        self.refresh_inventory()
        self.update_status()

    # --- enumeration (reference: GetGPUInfo, collector.go:40-79) ---

    def refresh_inventory(self) -> None:
        with self._lock:
            fresh = self.backend.list_devices()
            # Preserve ownership marks for devices that persist across
            # rescans (hot-unplug/replug keeps identity via uuid).
            old = {d.uuid: d for d in self.devices}
            for dev in fresh:
                prev = old.get(dev.uuid)
                if prev is not None and prev.pod_name:
                    dev.mark_allocated(prev.pod_name, prev.namespace)
            self.devices = fresh
            logger.info("TPU inventory: %d chip(s)", len(self.devices))

    # --- ownership refresh (reference: UpdateGPUStatus, collector.go:90-138) ---

    def _client(self) -> PodResourcesClient:
        if self._podresources is None:
            self._podresources = PodResourcesClient(
                self.cfg.kubelet_socket,
                timeout_s=self.cfg.kubelet_conn_timeout_s,
                api=self.cfg.pod_resources_api)
        return self._podresources

    def _match_device(self, device_id: str) -> TpuDevice | None:
        """Map a device-plugin ID to a chip. Lock must be held."""
        for dev in self.devices:
            if device_id == dev.uuid or device_id == dev.device_path:
                return dev
        m = _INDEX_RE.match(device_id)
        if m:
            idx = int(m.group(1))
            for dev in self.devices:
                if dev.index == idx:
                    return dev
        return None

    def update_status(self, strict: bool = False) -> None:
        """Refresh pod↔chip ownership from the kubelet.

        Degrades instead of failing when the kubelet socket is absent or
        the query errors (reference behavior: dial failure is tolerated
        per query, collector.go:92-103): the device inventory stays
        served, existing ownership marks are kept (marking everything
        free on a kubelet outage would hand owned chips to the
        allocator), and `ownership_known` flips to False. `strict=True`
        re-raises — for callers that must not act on stale data.
        """
        try:
            client = self._client()
            pod_resources = client.list()
        except Exception as exc:  # noqa: BLE001 — degrade like the reference
            if strict:
                raise
            with self._lock:
                self.ownership_known = False
            logger.warning(
                "pod-resources query failed (%s); serving device-only "
                "inventory, ownership unknown/stale", exc)
            return
        with self._lock:
            self.ownership_known = True
            for dev in self.devices:
                dev.reset_state()
            unmatched: list[str] = []
            for pod, ns, device_id in iter_device_claims(
                    pod_resources, self.cfg.tpu_resource_name):
                dev = self._match_device(device_id)
                if dev is None:
                    unmatched.append(device_id)
                    continue
                dev.mark_allocated(pod, ns)
            if unmatched:
                logger.warning("pod-resources advertises %s=%s not in local "
                               "inventory", self.cfg.tpu_resource_name,
                               unmatched)

    # --- queries ---

    def get_device_by_uuid(self, uuid: str) -> TpuDevice | None:
        # Reference: GetGPUByUUID (collector.go:81-88)
        with self._lock:
            for dev in self.devices:
                if dev.uuid == uuid:
                    return dev
        return None

    def free_devices(self) -> list[TpuDevice]:
        with self._lock:
            return [d for d in self.devices if not d.pod_name]

    def get_pod_devices(self, pod_name: str, namespace: str,
                        slave_pod_names: set[str] | None = None,
                        refresh: bool = True) -> list[TpuDevice]:
        """Chips owned by the pod, or by the named slave pods in the pool
        namespace.

        Reference analog: GetPodGPUResources (collector.go:149-163). The
        reference couples collector to allocator via a "<pod>-slave-pod-"
        name-prefix convention, which cross-talks between same-named pods
        in different namespaces; here the allocator passes the exact slave
        names it found via owner labels. With slave_pod_names=None, falls
        back to the prefix convention (CLI/debug use).
        """
        if refresh:
            self.update_status()
        # Matches the allocator's name construction (owner truncated to 200
        # chars before the suffix, allocator._slave_pod_manifest).
        slave_prefix = pod_name[:200] + self.cfg.slave_pod_name_suffix
        with self._lock:
            out = []
            for dev in self.devices:
                if not dev.pod_name:
                    continue
                if dev.pod_name == pod_name and dev.namespace == namespace:
                    out.append(dev)
                elif dev.namespace == self.cfg.pool_namespace and (
                        dev.pod_name in slave_pod_names
                        if slave_pod_names is not None
                        else dev.pod_name.startswith(slave_prefix)):
                    out.append(dev)
            return out

    def get_slave_pod_devices(self, slave_pod_name: str,
                              refresh: bool = True) -> list[TpuDevice]:
        """Chips the scheduler handed to one slave pod (allocator.go:85-96)."""
        if refresh:
            self.update_status()
        with self._lock:
            return [d for d in self.devices
                    if d.pod_name == slave_pod_name
                    and d.namespace == self.cfg.pool_namespace]

    def snapshot(self) -> list[TpuDevice]:
        """Copy of the inventory for read-only display (CLI, /devices)."""
        import copy
        with self._lock:
            return copy.deepcopy(self.devices)
