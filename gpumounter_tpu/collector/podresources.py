"""kubelet pod-resources API: messages, client, and an in-process fake.

Reference parity: the worker dials the kubelet's pod-resources unix socket
and calls PodResourcesLister.List to learn which pod owns which device
(collector.go:165-194, using the v1alpha1 generated client). Differences
here, per SURVEY.md §7:

  * We speak **v1 first** (modern kubelets; has cpu_ids/memory/topology)
    and fall back to **v1alpha1** (what the reference hardcodes,
    collector.go:16). The two versions share field numbers for everything
    we read, so one message set decodes both; only the gRPC service name
    differs (v1.PodResourcesLister vs v1alpha1.PodResourcesLister).
  * Messages ride our hand-rolled proto3 codec (rpc/wire.py) — no protoc,
    no generated code (the reference carries 481 generated lines).
  * The reference has no test substrate (SURVEY.md §4); FakeKubeletServer
    is a real gRPC server on a unix socket serving canned ListPodResources
    responses, so collector tests exercise the actual wire path.
"""

from __future__ import annotations

import os
from concurrent import futures

from gpumounter_tpu.rpc.wire import Field, Message
from gpumounter_tpu.utils.lazy_grpc import grpc
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("podresources")

# Full gRPC service names, per k8s.io/kubelet/pkg/apis/podresources.
SERVICE_V1 = "v1.PodResourcesLister"
SERVICE_V1ALPHA1 = "v1alpha1.PodResourcesLister"
LIST_METHOD = "List"


class TopologyInfo(Message):
    FIELDS = []  # NUMA nodes unused by us; unknown fields are skipped anyway


class ContainerDevices(Message):
    # v1 & v1alpha1: resource_name = 1, device_ids = 2 (v1 adds topology = 3)
    FIELDS = [
        Field(1, "resource_name", "string"),
        Field(2, "device_ids", "string", repeated=True),
    ]


class ContainerResources(Message):
    # v1 & v1alpha1: name = 1, devices = 2 (v1 adds cpu_ids = 3, memory = 4)
    FIELDS = [
        Field(1, "name", "string"),
        Field(2, "devices", "message", repeated=True, message=ContainerDevices),
    ]


class PodResources(Message):
    FIELDS = [
        Field(1, "name", "string"),
        Field(2, "namespace", "string"),
        Field(3, "containers", "message", repeated=True, message=ContainerResources),
    ]


class ListPodResourcesRequest(Message):
    FIELDS = []


class ListPodResourcesResponse(Message):
    FIELDS = [
        Field(1, "pod_resources", "message", repeated=True, message=PodResources),
    ]


class PodResourcesClient:
    """gRPC client for the kubelet pod-resources socket with version nego.

    Reference analog: connectToServer + ListPods (collector.go:165-194),
    which dials with a 10 s timeout and is pinned to v1alpha1.
    """

    def __init__(self, socket_path: str, timeout_s: float = 10.0,
                 api: str = "auto"):
        if not os.path.exists(socket_path):
            raise FileNotFoundError(
                f"kubelet pod-resources socket not found: {socket_path}")
        self.socket_path = socket_path
        self.timeout_s = timeout_s
        self._channel = grpc.insecure_channel(f"unix://{socket_path}")
        if api == "auto":
            self._services = [SERVICE_V1, SERVICE_V1ALPHA1]
        elif api == "v1":
            self._services = [SERVICE_V1]
        elif api == "v1alpha1":
            self._services = [SERVICE_V1ALPHA1]
        else:
            raise ValueError(f"unknown pod-resources api {api!r}")
        self._pinned: str | None = self._services[0] if len(self._services) == 1 else None

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _call_list(self, service: str) -> ListPodResourcesResponse:
        stub = self._channel.unary_unary(
            f"/{service}/{LIST_METHOD}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=ListPodResourcesResponse.decode)
        return stub(ListPodResourcesRequest(), timeout=self.timeout_s)

    def list(self) -> list[PodResources]:
        """ListPodResources; negotiates v1 → v1alpha1 on UNIMPLEMENTED."""
        if self._pinned is not None:
            return self._call_list(self._pinned).pod_resources
        last_err: Exception | None = None
        for service in self._services:
            try:
                resp = self._call_list(service)
                self._pinned = service
                logger.debug("pod-resources API pinned to %s", service)
                return resp.pod_resources
            except grpc.RpcError as exc:
                if exc.code() == grpc.StatusCode.UNIMPLEMENTED:
                    last_err = exc
                    continue
                raise
        raise RuntimeError(
            f"kubelet at {self.socket_path} serves no known pod-resources "
            f"API version: {last_err}")


def iter_device_claims(pod_resources: list[PodResources], resource_name: str):
    """Yield (pod_name, namespace, device_id) for a resource across pods.

    Reference analog: the loop marking devices allocated in UpdateGPUStatus
    (collector.go:113-135), filtered on ResourceName == "nvidia.com/gpu".
    """
    for pr in pod_resources:
        for container in pr.containers:
            for dev in container.devices:
                if dev.resource_name != resource_name:
                    continue
                for device_id in dev.device_ids:
                    yield pr.name, pr.namespace, device_id


class FakeKubeletServer:
    """In-process pod-resources gRPC server over a unix socket (tests/bench).

    Serves whichever API versions it is told to, so tests cover both the v1
    happy path and the v1alpha1 fallback. State is a mutable list of
    (pod_name, namespace, container, resource_name, [device_ids]).
    """

    def __init__(self, socket_path: str, versions: tuple[str, ...] = ("v1",)):
        self.socket_path = socket_path
        self.claims: list[tuple[str, str, str, str, list[str]]] = []
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        service_names = {"v1": SERVICE_V1, "v1alpha1": SERVICE_V1ALPHA1}
        for v in versions:
            handler = grpc.method_handlers_generic_handler(
                service_names[v],
                {LIST_METHOD: grpc.unary_unary_rpc_method_handler(
                    self._list,
                    request_deserializer=ListPodResourcesRequest.decode,
                    response_serializer=lambda m: m.encode())})
            self._server.add_generic_rpc_handlers((handler,))
        self._server.add_insecure_port(f"unix://{socket_path}")

    def _list(self, request, context) -> ListPodResourcesResponse:
        pods: dict[tuple[str, str], PodResources] = {}
        for pod, ns, container, resource, ids in self.claims:
            pr = pods.setdefault((ns, pod),
                                 PodResources(name=pod, namespace=ns))
            cr = next((c for c in pr.containers if c.name == container), None)
            if cr is None:
                cr = ContainerResources(name=container)
                pr.containers.append(cr)
            cr.devices.append(ContainerDevices(
                resource_name=resource, device_ids=list(ids)))
        return ListPodResourcesResponse(pod_resources=list(pods.values()))

    def set_claim(self, pod: str, namespace: str, resource: str,
                  device_ids: list[str], container: str = "main") -> None:
        self.claims.append((pod, namespace, container, resource, list(device_ids)))

    def clear(self) -> None:
        self.claims.clear()

    def start(self) -> "FakeKubeletServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=None)
