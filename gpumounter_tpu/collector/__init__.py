"""L5 collector: device inventory + pod↔device ownership map.

Reference parity: pkg/util/gpu/collector (collector.go:23-194).
"""

from gpumounter_tpu.collector.collector import TpuCollector
from gpumounter_tpu.collector.podresources import (
    FakeKubeletServer,
    PodResourcesClient,
)

__all__ = ["TpuCollector", "PodResourcesClient", "FakeKubeletServer"]
