"""Elastic reconciler: converge actual chip counts toward declared intents.

Master-side control loop, the controller-pattern counterpart of the
imperative /addtpu route:

    intent (pod annotations)      actual (worker's ProbeTPU RPC)
              \\                        /
               diff -> plan -> drive AddTPU / RemoveTPU
                        |
             workqueue: per-pod keys, exponential backoff
             with jitter on failure, global rate limit

Healing: the prober reports a chip dead (host node vanished/changed, or
the injected node disappeared from the target's /dev) -> the reconciler
force-removes it, mounts a healthy replacement through the slice
coordinator's all-or-nothing path, posts a TPUChipReplaced Event on the
owner pod, and stamps `tpumounter.io/chip-replaced` — the annotation
jaxside watches to trigger its HotResumable pack/restore cycle (the
CRIUgpu stance from PAPERS.md: accelerator state survives disruption).
"""

from __future__ import annotations

import json
import threading
import time

from gpumounter_tpu.config import get_config
from gpumounter_tpu.elastic.intents import (
    ANNOT_REPLACED,
    Intent,
    IntentError,
    IntentStore,
)
from gpumounter_tpu.elastic.workqueue import BackoffPolicy, RateLimitedQueue
from gpumounter_tpu.faults import failpoints
from gpumounter_tpu.k8s.client import (
    KubeClient,
    NotFoundError,
    patch_pod_with_retry,
)
from gpumounter_tpu.k8s.types import Pod
from gpumounter_tpu.obs import trace
from gpumounter_tpu.obs.audit import AUDIT
from gpumounter_tpu.rpc import api
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("elastic.reconciler")

RECONCILE_DURATION = REGISTRY.histogram(
    "tpumounter_reconcile_duration_seconds",
    "Wall time of one reconcile pass")
RECONCILE_QUEUE_DEPTH = REGISTRY.gauge(
    "tpumounter_reconcile_queue_depth",
    "Pods waiting in the elastic reconcile workqueue")
CHIPS_HEALED = REGISTRY.counter(
    "tpumounter_chips_healed_total",
    "Dead chips replaced with healthy ones by the reconciler")
CHIPS_HEAL_FAILURES = REGISTRY.counter(
    "tpumounter_chips_heal_failures_total",
    "Heal passes that found dead chips but failed before recording the "
    "heal (workqueue backoff re-drives them). With chips_healed_total "
    "this is the SLO engine's heal-success ratio (obs/slo.py)")
INTENTS_REGISTERED = REGISTRY.gauge(
    "tpumounter_intents_registered",
    "Pods with a declared elastic intent")


class ReconcileError(RuntimeError):
    """One pass failed; the key re-enters the queue with backoff."""


def _post_pod_event(kube: KubeClient, pod: Pod, reason: str, message: str,
                    event_type: str = "Normal") -> None:
    from gpumounter_tpu.k8s.events import post_pod_event
    post_pod_event(kube, pod, reason, message, event_type,
                   component="tpumounter-elastic")


class ElasticReconciler:
    def __init__(self, kube: KubeClient, registry, client_factory,
                 cfg=None, store: IntentStore | None = None,
                 backoff: BackoffPolicy | None = None, shards=None,
                 apihealth=None):
        """registry/client_factory: the MasterApp's WorkerRegistry and
        worker-client factory — the reconciler drives the same RPCs the
        imperative routes do. shards: optional ShardManager — when
        active, intents on nodes this replica does not own are parked
        (their shard's owner converges them). apihealth: the ApiHealth
        verdict (k8s/health.py) — while the API is degraded/down every
        pass is read-only (probe + report), because the intent and pod
        views may be stale and a destructive shrink driven from stale
        reads is exactly the corruption an outage must not cause."""
        self.cfg = cfg or get_config()
        self.kube = kube
        self.registry = registry
        self.client_factory = client_factory
        self.store = store or IntentStore(kube, self.cfg)
        self.shards = shards
        self.apihealth = apihealth
        self.queue = RateLimitedQueue(
            backoff=backoff or BackoffPolicy(
                base_s=self.cfg.elastic_backoff_base_s,
                cap_s=self.cfg.elastic_backoff_cap_s),
            min_interval_s=self.cfg.elastic_min_reconcile_interval_s,
            depth_gauge=RECONCILE_QUEUE_DEPTH)
        self.resync_interval_s = self.cfg.elastic_resync_interval_s
        #: key -> last outcome (served by GET /intents for observability)
        self.status: dict[str, dict] = {}
        #: key -> monotonic timestamps of recent passes (bounded; lets
        #: tests assert backoff spreads attempts instead of hot-looping)
        self.attempts: dict[str, list[float]] = {}
        #: key -> dead-chip uuids removed in passes whose replacement
        #: mount has not yet landed: a heal split across passes (remove
        #: succeeded, grow failed, retry mounted) must still be recorded
        #: — dropping it would leave jaxside unaware it has to repack.
        self._pending_heal: dict[str, list[str]] = {}
        self._status_lock = OrderedLock("elastic.status")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- lifecycle ---

    def start(self) -> "ElasticReconciler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="elastic-reconciler",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def enqueue(self, namespace: str, pod_name: str,
                priority: int = 0) -> None:
        self.queue.add(f"{namespace}/{pod_name}", priority=priority)

    def status_for(self, namespace: str, pod_name: str) -> dict | None:
        with self._status_lock:
            entry = self.status.get(f"{namespace}/{pod_name}")
            return dict(entry) if entry else None

    # --- the loop ---

    def _loop(self) -> None:
        next_resync = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_resync:
                self._resync()
                next_resync = now + self.resync_interval_s
            key = self.queue.get(
                timeout_s=min(0.2, max(0.01, next_resync - now)))
            if key is not None:
                self._process(key)

    def _resync(self) -> None:
        try:
            intents = self.store.list()
        except Exception as exc:  # noqa: BLE001 — keep the loop alive
            logger.warning("intent resync LIST failed: %s", exc)
            return
        INTENTS_REGISTERED.set(float(len(intents)))
        for namespace, pod_name, intent in intents:
            self.enqueue(namespace, pod_name, priority=intent.priority)

    def _process(self, key: str) -> None:
        namespace, _, pod_name = key.partition("/")
        started = time.monotonic()
        with self._status_lock:
            self.attempts.setdefault(key, []).append(started)
            del self.attempts[key][:-50]
        try:
            outcome = self.reconcile_once(namespace, pod_name)
        except Exception as exc:  # noqa: BLE001 — backoff instead of dying
            if not isinstance(exc, ReconcileError):
                logger.exception("unexpected reconcile failure for %s", key)
            delay = self.queue.retry(key)
            outcome = {"phase": "backoff", "error": str(exc),
                       "retry_in_s": round(delay, 3),
                       "failures": self.queue.failures(key)}
            logger.warning("reconcile %s failed (%s); retry in %.2fs",
                           key, exc, delay)
        else:
            if outcome.get("phase") in ("degraded", "migrating",
                                        "degraded-api"):
                # degraded: converged to >= min_chips but < desired —
                # keep trying for desired on the backoff schedule.
                # migrating: paused for an in-flight migration — check
                # back the same way until it finishes.
                # degraded-api: the API outage parked this pass
                # read-only — keep checking back until the API heals.
                self.queue.retry(key)
            else:
                self.queue.forget(key)
        finally:
            RECONCILE_DURATION.observe(time.monotonic() - started)
        with self._status_lock:
            if outcome.get("phase") == "gone":
                self.status.pop(key, None)
                self.attempts.pop(key, None)
            else:
                outcome["at"] = time.time()
                self.status[key] = outcome

    # --- one convergence pass (public: tests drive it directly) ---

    def reconcile_once(self, namespace: str, pod_name: str) -> dict:
        """One traced convergence pass. The loop has no inbound request,
        so the span mints a fresh trace id per pass — worker-side spans
        for the probes/removes/mounts it drives all join it (the heal
        audit record carries the same id).

        Deferred export: a converged steady-state resync (every
        elastic_resync_interval_s, per pod, forever) would rotate real
        operation traces out of the span ring — so a pass's spans are
        buffered and published only when the pass changed something or
        failed; no-op passes are dropped."""
        with trace.deferred() as pending:
            try:
                with trace.span("elastic.reconcile",
                                pod=f"{namespace}/{pod_name}"):
                    outcome = self._reconcile_traced(namespace, pod_name)
            except BaseException:
                pending.publish()
                raise
            if outcome.get("phase") not in ("converged", "unmanaged",
                                            "gone", "not-owned",
                                            "degraded-api") \
                    or outcome.get("healed") or outcome.get("added") \
                    or outcome.get("removed_excess"):
                pending.publish()
        return outcome

    def _reconcile_traced(self, namespace: str, pod_name: str) -> dict:
        key = f"{namespace}/{pod_name}"
        # Failpoint: a crash/error here models the reconciler dying at the
        # top of a pass — _process's boundary turns it into workqueue
        # backoff, the same recovery a restarted reconciler would get.
        failpoints.fire("elastic.reconcile", key=key)
        try:
            pod = Pod(self.kube.get_pod(namespace, pod_name))
        except NotFoundError:
            self.queue.forget(key)
            self._pending_heal.pop(key, None)
            return {"phase": "gone"}
        try:
            intent = Intent.from_annotations(pod.annotations)
        except IntentError as exc:
            # Permanent config error (hand-edited annotation): retrying
            # cannot fix it — park the key until the annotation changes
            # (the resync will re-enqueue; this pass stays cheap).
            self.queue.forget(key)
            logger.warning("invalid intent on %s: %s", key, exc)
            return {"phase": "invalid", "error": str(exc)}
        if intent is None:
            self.queue.forget(key)
            return {"phase": "unmanaged"}
        from gpumounter_tpu.migrate.journal import migration_active
        mid = migration_active(pod.annotations, kube=self.kube)
        if mid is not None:
            # A live migration owns this pod's chip set (source or
            # destination side); converging toward the intent now would
            # fight the orchestrator's drain/re-mount. Park the pass —
            # _process re-queues it on the backoff schedule, and the
            # resync keeps it coming back until the migration is
            # terminal.
            logger.info("reconcile of %s paused: migration %s in flight",
                        key, mid)
            return {"phase": "migrating", "migration": mid}
        if not pod.node_name:
            raise ReconcileError(f"pod {pod_name} is not scheduled yet")
        if self.shards is not None and self.shards.active() \
                and not self.shards.owns_node(pod.node_name):
            # Sharded masters: the node's shard owner reconciles this
            # intent — two replicas converging one pod would race their
            # probe/mount decisions. Parked, not retried: our resync
            # re-enqueues it, and after a takeover this branch flips.
            self.queue.forget(key)
            return {"phase": "not-owned",
                    "shard": self.shards.owner_shard(pod.node_name)}
        address = self.registry.worker_address(pod.node_name)
        if address is None:
            raise ReconcileError(
                f"no tpumounter worker on node {pod.node_name}")

        if self.apihealth is not None and not self.apihealth.ok():
            # Degraded-mode policy: the pass stays READ-ONLY. The probe
            # is a worker RPC (no API dependency) so the status surface
            # keeps reporting live chip counts, but mounts and — above
            # all — destructive shrinks are parked: the intent we just
            # read may be a stale cache entry, and removing chips a
            # user actually raised their intent for is unrecoverable.
            # _process re-queues on the backoff schedule; the pass
            # converges normally once the API heals.
            chips = self._probe(address, pod)
            healthy_now = [c for c in chips if c.healthy]
            logger.info("reconcile of %s parked read-only: api %s "
                        "(actual=%d desired=%d)", key,
                        self.apihealth.state(), len(healthy_now),
                        intent.desired_chips)
            return {"phase": "degraded-api",
                    "api": self.apihealth.state(),
                    "desired": intent.desired_chips,
                    "actual": len(healthy_now)}

        chips = self._probe(address, pod)
        dead = [c for c in chips if not c.healthy]
        healthy = [c for c in chips if c.healthy]
        if dead or self._pending_heal.get(key):
            return self._heal_counted(key, namespace, pod_name, pod,
                                      intent, address, dead, healthy)
        return self._converge(key, namespace, pod_name, pod, intent,
                              address, dead, healthy)

    def _node_epoch(self, pod: Pod) -> dict:
        """Fencing-epoch client kwargs for the pod's node: every
        mutation the reconciler drives carries it, so a replica whose
        shard lease was taken over cannot heal/shrink a pod its
        successor now manages (shard.epoch_kwargs is the shared rule)."""
        from gpumounter_tpu.master.shard import epoch_kwargs
        return epoch_kwargs(self.shards, pod.node_name)

    def _heal_counted(self, key, namespace, pod_name, pod, intent,
                      address, dead, healthy) -> dict:
        """A pass with dead chips (or a journaled half-done heal) is a
        heal attempt: a failure before _record_heal lands counts toward
        the heal-success SLO (the workqueue still re-drives it)."""
        try:
            return self._converge(key, namespace, pod_name, pod, intent,
                                  address, dead, healthy)
        except BaseException:
            CHIPS_HEAL_FAILURES.inc()
            raise

    def _converge(self, key, namespace, pod_name, pod, intent, address,
                  dead, healthy) -> dict:
        removed_now = self._remove_chips(
            address, pod, [c.uuid for c in dead], force=True)
        # Journal removals BEFORE attempting the replacement mount: if
        # this pass dies in _grow, the retry pass sees no dead chips any
        # more, and without the journal the heal would never be recorded
        # (no chip-replaced marker -> jaxside never repacks).
        pending = self._pending_heal.setdefault(key, [])
        pending.extend(u for u in removed_now if u not in pending)
        removed_dead = list(pending)

        actual = len(healthy)
        desired = intent.desired_chips
        degraded = False
        if actual < desired:
            # Crash site between the journaled removal above and the
            # replacement mount: the _pending_heal journal must carry the
            # heal record across the induced retry.
            failpoints.fire("elastic.before_grow", key=key,
                            gap=desired - actual)
            degraded = not self._grow(address, pod, intent,
                                      desired - actual, actual)
        removed_excess: list[str] = []
        if actual > desired:
            # Declarative scale-down: force is the designed path — libtpu
            # holds chips for the life of the JAX process, so a polite
            # remove would always report Busy (SURVEY.md §7).
            excess = [c.uuid for c in healthy[desired:]]
            removed_excess = self._remove_chips(address, pod, excess,
                                                force=True)

        after = self._probe(address, pod)
        healthy_after = [c for c in after if c.healthy]
        added = sorted({c.uuid for c in healthy_after}
                       - {c.uuid for c in healthy})
        if removed_dead:
            self._record_heal(pod, removed_dead, added)
            self._pending_heal.pop(key, None)

        outcome = {
            "phase": "degraded" if degraded else "converged",
            "desired": desired,
            "actual": len(healthy_after),
            "healed": len(removed_dead),
            "removed_dead": removed_dead,
            "removed_excess": removed_excess,
            "added": added,
        }
        if not degraded and len(healthy_after) != desired:
            # The cluster moved under us between probe and re-probe;
            # surface it and let the backoff schedule re-drive.
            raise ReconcileError(
                f"post-reconcile count {len(healthy_after)} != desired "
                f"{desired} for {namespace}/{pod_name}")
        logger.info("reconciled %s/%s: %s", namespace, pod_name, outcome)
        return outcome

    # --- steps ---

    def _probe(self, address: str, pod: Pod) -> list[api.ChipHealth]:
        try:
            with self.client_factory(address) as client:
                result, chips = client.probe_tpu(pod.name, pod.namespace)
        except Exception as exc:  # noqa: BLE001 — gRPC boundary
            raise ReconcileError(f"probe RPC failed: {exc}")
        if result != api.ProbeTPUResult.Success:
            raise ReconcileError(f"probe returned {result.name}")
        return chips

    def _remove_chips(self, address: str, pod: Pod, uuids: list[str],
                      force: bool) -> list[str]:
        removed: list[str] = []
        epoch_kwargs = self._node_epoch(pod)
        for uuid in uuids:
            try:
                with self.client_factory(address) as client:
                    result = client.remove_tpu(pod.name, pod.namespace,
                                               [uuid], force=force,
                                               **epoch_kwargs)
            except Exception as exc:  # noqa: BLE001 — gRPC boundary
                raise ReconcileError(f"remove of {uuid} failed: {exc}")
            if result not in (api.RemoveTPUResult.Success,
                              api.RemoveTPUResult.TPUNotFound):
                raise ReconcileError(
                    f"remove of {uuid} returned {result.name}")
            removed.append(uuid)
        return removed

    def _grow(self, address: str, pod: Pod, intent: Intent, gap: int,
              actual: int) -> bool:
        """Mount `gap` chips through the slice coordinator's
        all-or-nothing path (its rollback covers multi-chip deltas and
        transport-level failures). Returns True when desired was reached,
        False when only the min_chips floor could be satisfied."""
        from gpumounter_tpu.master.slice_ops import (
            SliceCoordinator,
            SliceError,
            SliceTarget,
        )

        coordinator = SliceCoordinator(self.kube, self.registry,
                                       self.client_factory, self.cfg,
                                       shards=self.shards)
        target = SliceTarget(namespace=pod.namespace, pod=pod.name)
        try:
            coordinator.mount_slice([target], gap, entire=False)
            return True
        except SliceError as exc:
            # A degraded worker (circuit open, retry_after_s set) is also
            # 503 but is NOT capacity exhaustion — back off, don't start
            # shrinking toward the min_chips floor.
            if exc.status != 503 or exc.retry_after_s is not None:  # tpulint: allow[typed-k8s-errors] SliceError.status is the master's own
                # HTTP status, not a k8s API code
                raise ReconcileError(f"mount of {gap} chip(s) failed: {exc}")
        # Capacity exhausted. Already at or above the declared floor:
        # that is the documented "degraded, not failed" state — keep
        # retrying for desired on the backoff schedule without
        # alarming (and without stamping a rejection verdict every
        # backoff pass: N capacity-limited intents would flood the
        # bounded audit ring with identical records — only the
        # below-floor TRUE failures below record).
        floor_gap = intent.min_chips - actual
        if floor_gap <= 0:
            logger.warning(
                "capacity-limited: %s/%s holds %d >= min_chips %d "
                "(desired %d); will keep retrying", pod.namespace,
                pod.name, actual, intent.min_chips, intent.desired_chips)
            return False
        # Below the floor: a smaller mount may still satisfy it. These
        # are TRUE capacity failures (the intent cannot even reach its
        # declared floor), so they stamp the feasibility verdict into
        # the audit trail / flight recorder (obs/capacity.py — no-op
        # when no capacity plane is registered): the incident timeline
        # says whether fragmentation or exhaustion blocked the grow.
        from gpumounter_tpu.obs import capacity as capacity_obs
        if floor_gap < gap:
            try:
                coordinator.mount_slice([target], floor_gap, entire=False)
                logger.warning(
                    "capacity-limited: %s/%s at min_chips floor %d "
                    "(desired %d); will keep retrying", pod.namespace,
                    pod.name, intent.min_chips, intent.desired_chips)
                return False
            except SliceError as exc:
                capacity_obs.record_rejection(
                    pod.node_name, pod.namespace, pod.name, floor_gap)
                raise ReconcileError(
                    f"floor mount of {floor_gap} chip(s) failed: {exc}")
        capacity_obs.record_rejection(pod.node_name, pod.namespace,
                                      pod.name, gap)
        raise ReconcileError(
            f"insufficient capacity for {gap} chip(s) "
            f"(actual={actual}, min={intent.min_chips})")

    def _record_heal(self, pod: Pod, removed: list[str],
                     added: list[str]) -> None:
        CHIPS_HEALED.inc(len(removed))
        AUDIT.record(
            "elastic.heal", actor="reconciler", namespace=pod.namespace,
            pod=pod.name, chips=added, outcome="success",
            removed=sorted(removed))
        previous = {}
        try:
            previous = json.loads(pod.annotations.get(ANNOT_REPLACED, "{}"))
        except ValueError:
            pass
        marker = {
            "generation": int(previous.get("generation", 0)) + 1,
            "removed": removed,
            "added": added,
            # The reconcile pass's trace id: the jaxside telemetry SDK
            # stamps it onto the heal disruption window, attributing the
            # tenant's repack/restore gap to THIS heal's trace.
            "trace_id": trace.current_trace_id(),
            "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        try:
            patch_pod_with_retry(
                self.kube, pod.namespace, pod.name,
                {"metadata": {"annotations": {
                    ANNOT_REPLACED: json.dumps(marker)}}},
                attempts=self.cfg.k8s_write_attempts,
                base_s=self.cfg.k8s_write_retry_base_s)
        except Exception as exc:  # noqa: BLE001 — marker is advisory
            logger.warning("chip-replaced annotation patch failed: %s", exc)
        _post_pod_event(
            self.kube, pod, "TPUChipReplaced",
            f"replaced {len(removed)} dead chip(s) "
            f"{', '.join(removed)} with {', '.join(added) or '(pending)'}",
            event_type="Warning")
        logger.info("healed %s/%s: removed %s added %s",
                    pod.namespace, pod.name, removed, added)
