"""Rate-limited workqueue with per-key exponential backoff.

The shape client-go controllers are built on (workqueue +
rate-limiter), reduced to what the elastic reconciler needs:

  * per-pod keys, deduplicated while queued — N intent edits for one pod
    cost one reconcile pass;
  * per-key exponential backoff with jitter on failure, reset on
    success — a pod whose mounts keep failing retries at 0.5s, 1s, 2s,
    ... up to a cap instead of hot-looping the worker;
  * a global floor between dequeues — one sick intent cannot starve the
    API server or the workers of everything else;
  * priority breaks ties among keys that are ready at the same moment.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class BackoffPolicy:
    base_s: float = 0.5
    factor: float = 2.0
    cap_s: float = 60.0
    #: fraction of the delay added uniformly at random, so a thundering
    #: herd of same-aged failures decorrelates.
    jitter: float = 0.1

    def delay_for(self, failures: int) -> float:
        if failures <= 0:
            return 0.0
        delay = min(self.base_s * self.factor ** (failures - 1), self.cap_s)
        if self.jitter:
            delay *= 1.0 + random.uniform(0.0, self.jitter)
        return delay


class RateLimitedQueue:
    def __init__(self, backoff: BackoffPolicy | None = None,
                 min_interval_s: float = 0.0,
                 depth_gauge=None):
        self.backoff = backoff or BackoffPolicy()
        self.min_interval_s = min_interval_s
        self._depth_gauge = depth_gauge
        self._lock = threading.Condition()
        self._heap: list[tuple[float, int, int, str]] = []  # (ready, -prio, seq, key)
        self._queued: set[str] = set()
        self._failures: dict[str, int] = {}
        #: last declared priority per key — retries must keep competing
        #: at the intent's priority, not fall back to 0.
        self._priority: dict[str, int] = {}
        self._seq = itertools.count()
        self._last_pop = 0.0

    # --- producers ---

    def add(self, key: str, priority: int = 0, delay_s: float = 0.0) -> None:
        """Enqueue; a key already waiting is not enqueued twice (but a key
        currently being processed may re-queue — standard dirty/processing
        workqueue semantics, collapsed to "dedupe while queued")."""
        with self._lock:
            self._priority[key] = priority
            if key in self._queued:
                return
            self._queued.add(key)
            heapq.heappush(self._heap, (time.monotonic() + delay_s,
                                        -priority, next(self._seq), key))
            self._update_depth()
            self._lock.notify_all()

    def retry(self, key: str, priority: int | None = None) -> float:
        """Re-enqueue after a failure with the key's next backoff delay
        (at its last declared priority unless overridden); returns the
        delay chosen."""
        with self._lock:
            failures = self._failures.get(key, 0) + 1
            self._failures[key] = failures
            if priority is None:
                priority = self._priority.get(key, 0)
        delay = self.backoff.delay_for(failures)
        self.add(key, priority=priority, delay_s=delay)
        return delay

    def forget(self, key: str) -> None:
        """Success (or key gone): reset the key's backoff history.
        The remembered priority goes too — the next add() (resync or
        intent edit) re-declares it, and keys for deleted pods must not
        accumulate state forever."""
        with self._lock:
            self._failures.pop(key, None)
            if key not in self._queued:
                self._priority.pop(key, None)

    def failures(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    # --- consumer ---

    def get(self, timeout_s: float) -> str | None:
        """Next ready key, honoring per-key ready times and the global
        rate-limit floor; None when nothing becomes ready in time."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while True:
                now = time.monotonic()
                wait = deadline - now
                if self._heap:
                    first_ready = max(self._heap[0][0],
                                      self._last_pop + self.min_interval_s)
                    if first_ready <= now:
                        # Everything whose ready time has passed competes
                        # on priority (the heap alone would serve oldest
                        # first regardless of priority).
                        ready = []
                        while self._heap and self._heap[0][0] <= now:
                            ready.append(heapq.heappop(self._heap))
                        ready.sort(key=lambda item: (item[1], item[2]))
                        chosen = ready.pop(0)
                        for item in ready:
                            heapq.heappush(self._heap, item)
                        key = chosen[3]
                        self._queued.discard(key)
                        self._last_pop = now
                        self._update_depth()
                        return key
                    wait = min(wait, first_ready - now)
                if wait <= 0:
                    return None
                self._lock.wait(wait)

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def _update_depth(self) -> None:  # caller holds _lock
        if self._depth_gauge is not None:
            self._depth_gauge.set(float(len(self._heap)))
