"""Elastic intent controller: declarative chip counts with health-probing,
self-healing reconciliation. See intents.py (store), workqueue.py
(backoff/rate-limit queue), reconciler.py (the loop)."""

from gpumounter_tpu.elastic.intents import (
    ANNOT_DESIRED,
    ANNOT_MIN,
    ANNOT_PRIORITY,
    ANNOT_REPLACED,
    Intent,
    IntentError,
    IntentStore,
)
from gpumounter_tpu.elastic.reconciler import ElasticReconciler, ReconcileError
from gpumounter_tpu.elastic.workqueue import BackoffPolicy, RateLimitedQueue

__all__ = [
    "ANNOT_DESIRED",
    "ANNOT_MIN",
    "ANNOT_PRIORITY",
    "ANNOT_REPLACED",
    "BackoffPolicy",
    "ElasticReconciler",
    "Intent",
    "IntentError",
    "IntentStore",
    "RateLimitedQueue",
    "ReconcileError",
]
