"""Intent store: declarative per-pod chip counts, persisted as annotations.

No reference analog — GPUMounter is purely imperative (one /addgpu call
per mount; SURVEY.md §5 "no reconciliation at all"). Here clients declare
*desired* state and the reconciler converges toward it, the way FlexNPU
reallocates accelerators between colocated workloads (PAPERS.md).

The store has no database: the pod object IS the record. Intents live in
annotations on the target pod (`tpumounter.io/desired-chips`, ...), so

  * they survive master restarts and re-elections for free,
  * `kubectl annotate` is a valid (if raw) client,
  * deleting the pod deletes its intent — no orphaned desires.
"""

from __future__ import annotations

from dataclasses import dataclass

from gpumounter_tpu.config import get_config
from gpumounter_tpu.k8s.client import KubeClient
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("elastic.intents")

ANNOT_DESIRED = "tpumounter.io/desired-chips"
ANNOT_MIN = "tpumounter.io/min-chips"
ANNOT_PRIORITY = "tpumounter.io/priority"
#: stamped by the reconciler after a heal; jaxside watches it to trigger
#: the HotResumable pack/restore cycle (jaxside/heal.py).
ANNOT_REPLACED = "tpumounter.io/chip-replaced"


class IntentError(ValueError):
    """Client-supplied intent is malformed (maps to HTTP 400)."""


@dataclass(frozen=True)
class Intent:
    desired_chips: int
    #: acceptable floor under capacity pressure: the reconciler keeps
    #: retrying for desired_chips but treats >= min_chips as "degraded",
    #: not "failed". 0 = desired is all-or-nothing best effort.
    min_chips: int = 0
    #: higher reconciles first when the queue is contended.
    priority: int = 0

    def validate(self, max_chips: int) -> "Intent":
        if not 0 <= self.desired_chips <= max_chips:
            raise IntentError(
                f"desired_chips must be 0..{max_chips}, "
                f"got {self.desired_chips}")
        if not 0 <= self.min_chips <= self.desired_chips:
            raise IntentError(
                f"min_chips must be 0..desired_chips "
                f"({self.desired_chips}), got {self.min_chips}")
        return self

    @classmethod
    def from_annotations(cls, annotations: dict[str, str]) -> "Intent | None":
        raw = annotations.get(ANNOT_DESIRED)
        if raw is None:
            return None
        try:
            return cls(desired_chips=int(raw),
                       min_chips=int(annotations.get(ANNOT_MIN, "0")),
                       priority=int(annotations.get(ANNOT_PRIORITY, "0")))
        except ValueError as exc:
            raise IntentError(f"malformed intent annotations: {exc}")

    def to_annotations(self) -> dict[str, str]:
        return {ANNOT_DESIRED: str(self.desired_chips),
                ANNOT_MIN: str(self.min_chips),
                ANNOT_PRIORITY: str(self.priority)}

    def to_json(self) -> dict:
        return {"desiredChips": self.desired_chips,
                "minChips": self.min_chips, "priority": self.priority}

    @classmethod
    def from_json(cls, payload: dict) -> "Intent":
        if not isinstance(payload, dict):
            raise IntentError('body must be a JSON object with "desiredChips"')
        try:
            desired = int(payload["desiredChips"])
            minimum = int(payload.get("minChips", 0))
            priority = int(payload.get("priority", 0))
        except KeyError:
            raise IntentError('missing required field "desiredChips"')
        except (TypeError, ValueError) as exc:
            raise IntentError(f"intent fields must be integers: {exc}")
        return cls(desired_chips=desired, min_chips=minimum,
                   priority=priority)


class IntentStore:
    """CRUD over intent annotations. Raises k8s NotFoundError when the
    target pod does not exist (the intent has nothing to live on).

    Persistence is delegated to a MasterStore backend (store/base.py) —
    by default the annotation-persisted KubeMasterStore, so the intent
    API is unchanged while the actual state lives behind the seam any
    stateless master replica rebuilds from."""

    def __init__(self, kube: KubeClient, cfg=None, backend=None):
        self.kube = kube
        self.cfg = cfg or get_config()
        if backend is None:
            from gpumounter_tpu.store import KubeMasterStore
            backend = KubeMasterStore(kube, self.cfg)
        self.backend = backend

    def put(self, namespace: str, pod_name: str, intent: Intent) -> Intent:
        intent.validate(self.cfg.max_tpu_per_request)
        self.backend.put_intent(namespace, pod_name, intent)
        logger.info("intent set: %s/%s desired=%d min=%d priority=%d",
                    namespace, pod_name, intent.desired_chips,
                    intent.min_chips, intent.priority)
        return intent

    def get(self, namespace: str, pod_name: str) -> Intent | None:
        return self.backend.get_intent(namespace, pod_name)

    def delete(self, namespace: str, pod_name: str) -> bool:
        """Remove the intent (and the heal marker); the pod keeps its
        currently-mounted chips — deletion stops management, it does not
        unmount. Returns whether an intent was present."""
        had = self.backend.delete_intent(namespace, pod_name)
        if had:
            logger.info("intent deleted: %s/%s", namespace, pod_name)
        return had

    def list(self) -> list[tuple[str, str, Intent]]:
        """Every (namespace, pod, intent) in the cluster — one LIST, used
        by the reconciler's periodic resync."""
        return self.backend.list_intents()
