"""Device backends: real /dev/accel* enumeration and a fake for dry-runs.

Replaces the reference's NVML enumeration path (collector.go:40-79 calling
nvml.Init / DeviceGetCount / GetHandleByIndex / MinorNumber / UUID through the
cgo dlopen shim nvml_dl.go:29-36). TPU chips appear as accel-class character
devices; no driver library is required to enumerate them — readdir + stat(2)
+ sysfs reads suffice, with an optional native fast path (see native.py).

Busy detection replaces NVML's GetComputeRunningProcesses (nvml.go:33-52):
scan /proc/<pid>/fd for open descriptors whose target is the device node
(matched by rdev, so it works across mount namespaces / renamed device
files). Note TPU runtime semantics: libtpu holds the chip open for the life
of the JAX process, so "busy" is the common case (SURVEY.md §7) — remove
flows lean on `force`.
"""

from __future__ import annotations

import abc
import json
import os
import re
import stat as statmod

from gpumounter_tpu.device.tpu import TpuDevice, stat_device_numbers
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("device")

_ACCEL_RE = re.compile(r"^accel(\d+)$")
# vfio-based TPU VMs expose /dev/vfio/<group>; accel class is the modern path.
_VFIO_RE = re.compile(r"^(\d+)$")


class DeviceBackend(abc.ABC):
    """Enumeration + identity + busy primitives behind one interface."""

    @abc.abstractmethod
    def list_devices(self) -> list[TpuDevice]: ...

    def device_by_uuid(self, uuid: str) -> TpuDevice | None:
        for dev in self.list_devices():
            if dev.uuid == uuid:
                return dev
        return None

    def running_pids(self, device: TpuDevice) -> list[int]:
        """PIDs (host view) holding the device node open."""
        return scan_proc_for_device(device.major, device.minor,
                                    path_hint=device.device_path)


class RealAccelBackend(DeviceBackend):
    """Enumerates accel-class TPU chardevs under device_dir (default /dev).

    Identity: sysfs PCI address when available
    (/sys/class/accel/accelN/device is a symlink into the PCI tree), else
    "tpu-<node>-accelN". The reference's analog is the NVML UUID
    (nvml.go:107-119); PCI addresses are the TPU-native stable handle and
    are what the GKE TPU device-plugin topology is keyed on.
    """

    def __init__(self, device_dir: str = "/dev",
                 sysfs_accel_dir: str = "/sys/class/accel"):
        self.device_dir = device_dir
        self.sysfs_accel_dir = sysfs_accel_dir

    def _chip_uuid(self, name: str, index: int) -> str:
        dev_link = os.path.join(self.sysfs_accel_dir, name, "device")
        try:
            target = os.readlink(dev_link)
            pci = os.path.basename(target)
            if pci:
                return f"tpu-pci-{pci}"
        except OSError:
            pass
        node = os.uname().nodename
        return f"tpu-{node}-accel{index}"

    def list_devices(self) -> list[TpuDevice]:
        devices: list[TpuDevice] = []
        try:
            names = sorted(os.listdir(self.device_dir))
        except FileNotFoundError:
            return []
        for name in names:
            m = _ACCEL_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.device_dir, name)
            try:
                major, minor, is_char = stat_device_numbers(path)
            except OSError:
                continue
            if not is_char:
                continue
            index = int(m.group(1))
            devices.append(TpuDevice(
                index=index, device_path=path, major=major, minor=minor,
                uuid=self._chip_uuid(name, index)))
        devices.sort(key=lambda d: d.index)
        return devices


class FakeDeviceBackend(DeviceBackend):
    """Fake chip inventory over a plain directory (BASELINE config 1).

    Layout: <dir>/accelN are the "device nodes". When the process has
    CAP_MKNOD they are real char devices cloned from /dev/null's rdev so the
    whole mount path (cgroup grant + mknod into the container) is exercised
    for real; otherwise regular files with pseudo major:minor recorded in
    <dir>/meta.json so enumeration logic still runs everywhere.
    """

    META = "meta.json"

    def __init__(self, root: str):
        self.root = root

    @classmethod
    def create(cls, root: str, count: int) -> "FakeDeviceBackend":
        os.makedirs(root, exist_ok=True)
        meta: dict[str, dict] = {}
        null_rdev = None
        try:
            st = os.stat("/dev/null")
            if statmod.S_ISCHR(st.st_mode):
                null_rdev = st.st_rdev
        except OSError:
            pass
        for i in range(count):
            path = os.path.join(root, f"accel{i}")
            if os.path.exists(path):
                continue
            made = False
            if null_rdev is not None:
                try:
                    os.mknod(path, 0o666 | statmod.S_IFCHR, null_rdev)
                    made = True
                except (OSError, PermissionError):
                    made = False
            if not made:
                with open(path, "w"):
                    pass
                meta[f"accel{i}"] = {"major": 1, "minor": 100 + i}
        if meta:
            meta_path = os.path.join(root, cls.META)
            existing = {}
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    existing = json.load(f)
            existing.update(meta)
            with open(meta_path, "w") as f:
                json.dump(existing, f)
        return cls(root)

    def _meta(self) -> dict:
        path = os.path.join(self.root, self.META)
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        return {}

    def list_devices(self) -> list[TpuDevice]:
        meta = self._meta()
        devices = []
        try:
            names = sorted(os.listdir(self.root))
        except FileNotFoundError:
            return []
        for name in names:
            m = _ACCEL_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.root, name)
            index = int(m.group(1))
            try:
                major, minor, is_char = stat_device_numbers(path)
            except OSError:
                continue
            if not is_char:
                fake = meta.get(name, {})
                major = fake.get("major", 1)
                minor = fake.get("minor", 100 + index)
            devices.append(TpuDevice(
                index=index, device_path=path, major=major, minor=minor,
                uuid=f"tpu-fake-accel{index}"))
        devices.sort(key=lambda d: d.index)
        return devices

    def running_pids(self, device: TpuDevice) -> list[int]:
        # Fake devices cloned from /dev/null share its rdev; rdev matching
        # would report every process holding /dev/null. Match by path only.
        return scan_proc_for_device(None, None, path_hint=device.device_path)


def scan_proc_for_device(major: int | None, minor: int | None,
                         path_hint: str = "", proc_root: str = "/proc") -> list[int]:
    """PIDs with an open fd on the given device (by rdev and/or path).

    Uses the native scanner (native/tpumounter_native.cpp) when built —
    this sits on the busy-check hot path of every unmount — with this
    Python implementation as the always-available fallback. Matching by
    st_rdev catches the device regardless of the path the opener used
    (bind mounts, different mount namespaces).
    """
    from gpumounter_tpu import native as native_mod
    native_pids = native_mod.scan_device_holders(major, minor, path_hint,
                                                 proc_root)
    if native_pids is not None:
        return native_pids
    pids: list[int] = []
    want_rdev = None
    if major is not None and minor is not None and (major, minor) != (0, 0):
        want_rdev = os.makedev(major, minor)
    try:
        entries = os.listdir(proc_root)
    except FileNotFoundError:
        return []
    for entry in entries:
        if not entry.isdigit():
            continue
        fd_dir = os.path.join(proc_root, entry, "fd")
        try:
            fds = os.listdir(fd_dir)
        except OSError:
            continue
        for fd in fds:
            fd_path = os.path.join(fd_dir, fd)
            matched = False
            if want_rdev is not None:
                try:
                    st = os.stat(fd_path)
                    if statmod.S_ISCHR(st.st_mode) and st.st_rdev == want_rdev:
                        matched = True
                except OSError:
                    pass
            if not matched and path_hint:
                try:
                    if os.readlink(fd_path) == path_hint:
                        matched = True
                except OSError:
                    pass
            if matched:
                pids.append(int(entry))
                break
    return pids


def backend_from_config(cfg=None) -> DeviceBackend:
    from gpumounter_tpu.config import get_config
    cfg = cfg or get_config()
    if cfg.fake_device_dir:
        return FakeDeviceBackend(cfg.fake_device_dir)
    return RealAccelBackend(cfg.device_dir)
