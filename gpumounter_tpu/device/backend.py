"""Device backends: real /dev/accel* enumeration and a fake for dry-runs.

Replaces the reference's NVML enumeration path (collector.go:40-79 calling
nvml.Init / DeviceGetCount / GetHandleByIndex / MinorNumber / UUID through the
cgo dlopen shim nvml_dl.go:29-36). TPU chips appear as accel-class character
devices; no driver library is required to enumerate them — readdir + stat(2)
+ sysfs reads suffice, with an optional native fast path (see native.py).

Busy detection replaces NVML's GetComputeRunningProcesses (nvml.go:33-52):
scan /proc/<pid>/fd for open descriptors whose target is the device node
(matched by rdev, so it works across mount namespaces / renamed device
files). Note TPU runtime semantics: libtpu holds the chip open for the life
of the JAX process, so "busy" is the common case (SURVEY.md §7) — remove
flows lean on `force`.
"""

from __future__ import annotations

import abc
import json
import os
import re
import stat as statmod

from gpumounter_tpu.device.tpu import (
    CompanionNode,
    TpuDevice,
    stat_device_numbers,
)
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("device")

_ACCEL_RE = re.compile(r"^accel(\d+)$")
# vfio-based TPU VMs expose one IOMMU-group chardev per chip under
# /dev/vfio/<group> plus the shared container node /dev/vfio/vfio; the
# accel class is the modern path. Both are enumerated.
_VFIO_RE = re.compile(r"^(\d+)$")
VFIO_SUBDIR = "vfio"
VFIO_CONTAINER = "vfio"  # /dev/vfio/vfio


class DeviceBackend(abc.ABC):
    """Enumeration + identity + busy primitives behind one interface."""

    @abc.abstractmethod
    def list_devices(self) -> list[TpuDevice]: ...

    def device_by_uuid(self, uuid: str) -> TpuDevice | None:
        for dev in self.list_devices():
            if dev.uuid == uuid:
                return dev
        return None

    def running_pids(self, device: TpuDevice) -> list[int]:
        """PIDs (host view) holding the device node open."""
        return scan_proc_for_device(device.major, device.minor,
                                    path_hint=device.device_path)

    def probe_device(self, device: TpuDevice) -> tuple[bool, str]:
        """(healthy, reason) for one chip — the worker-side health probe.

        A chip is dead when its host device node vanished, stopped being
        a character device, or changed identity (major:minor moved: the
        driver re-enumerated and this handle now points elsewhere).
        """
        try:
            major, minor, is_char = stat_device_numbers(device.device_path)
        except OSError as exc:
            return False, f"device node stat failed: {exc}"
        if not is_char:
            return False, "device node is no longer a character device"
        if (major, minor) != (device.major, device.minor):
            return False, (f"device identity changed: {major}:{minor} != "
                           f"{device.major}:{device.minor}")
        return True, ""


class RealAccelBackend(DeviceBackend):
    """Enumerates accel-class TPU chardevs under device_dir (default /dev).

    Identity: sysfs PCI address when available
    (/sys/class/accel/accelN/device is a symlink into the PCI tree), else
    "tpu-<node>-accelN". The reference's analog is the NVML UUID
    (nvml.go:107-119); PCI addresses are the TPU-native stable handle and
    are what the GKE TPU device-plugin topology is keyed on.
    """

    def __init__(self, device_dir: str = "/dev",
                 sysfs_accel_dir: str = "/sys/class/accel",
                 sysfs_iommu_dir: str = "/sys/kernel/iommu_groups"):
        self.device_dir = device_dir
        self.sysfs_accel_dir = sysfs_accel_dir
        self.sysfs_iommu_dir = sysfs_iommu_dir

    def _chip_uuid(self, name: str, index: int) -> str:
        dev_link = os.path.join(self.sysfs_accel_dir, name, "device")
        try:
            target = os.readlink(dev_link)
            pci = os.path.basename(target)
            if pci:
                return f"tpu-pci-{pci}"
        except OSError:
            pass
        node = os.uname().nodename
        return f"tpu-{node}-accel{index}"

    def list_devices(self) -> list[TpuDevice]:
        devices: list[TpuDevice] = []
        try:
            names = sorted(os.listdir(self.device_dir))
        except FileNotFoundError:
            return []
        for name in names:
            m = _ACCEL_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.device_dir, name)
            try:
                major, minor, is_char = stat_device_numbers(path)
            except OSError:
                continue
            if not is_char:
                continue
            index = int(m.group(1))
            devices.append(TpuDevice(
                index=index, device_path=path, major=major, minor=minor,
                uuid=self._chip_uuid(name, index)))
        if not devices:
            # vfio is the LEGACY TPU exposure; a host has accel-class
            # nodes or vfio nodes, never both. Gating on "no accel" keeps
            # indexes collision-free and avoids enumerating unrelated
            # vfio groups (e.g. a passthrough NIC) on accel hosts.
            devices.extend(self._list_vfio())
        devices.sort(key=lambda d: d.index)
        return devices

    # PCI vendor id of Google TPU chips (sysfs `vendor` content).
    _GOOGLE_PCI_VENDOR = "0x1ae0"

    def _vfio_group_is_tpu(self, group: int) -> bool:
        """Only groups whose members are Google PCI devices are TPUs —
        other vfio-bound hardware (NIC passthrough etc.) must not be
        handed to tenants as chips."""
        members_dir = os.path.join(self.sysfs_iommu_dir, str(group),
                                   "devices")
        try:
            members = os.listdir(members_dir)
        except OSError:
            return False
        for member in members:
            try:
                with open(os.path.join(members_dir, member, "vendor")) as f:
                    if f.read().strip().lower() == self._GOOGLE_PCI_VENDOR:
                        return True
            except OSError:
                continue
        return False

    def _vfio_uuid(self, group: int) -> str:
        """Stable identity for a vfio group: the PCI address(es) of its
        members (/sys/kernel/iommu_groups/<N>/devices/ entries)."""
        members_dir = os.path.join(self.sysfs_iommu_dir, str(group),
                                   "devices")
        try:
            members = sorted(os.listdir(members_dir))
        except OSError:
            members = []
        if members:
            return "tpu-pci-" + "+".join(members)
        return f"tpu-{os.uname().nodename}-vfio{group}"

    def _list_vfio(self) -> list[TpuDevice]:
        """vfio-based TPU VMs: one chardev per IOMMU group; the shared
        /dev/vfio/vfio container node travels as a companion (VERDICT r1
        missing #4 — previously claimed but dead code)."""
        vfio_dir = os.path.join(self.device_dir, VFIO_SUBDIR)
        try:
            names = sorted(os.listdir(vfio_dir))
        except OSError:
            return []
        companions: list[CompanionNode] = []
        container_path = os.path.join(vfio_dir, VFIO_CONTAINER)
        try:
            cmaj, cmin, is_char = stat_device_numbers(container_path)
            if is_char:
                companions = [CompanionNode(
                    rel_path=f"{VFIO_SUBDIR}/{VFIO_CONTAINER}",
                    major=cmaj, minor=cmin)]
        except OSError:
            pass
        devices: list[TpuDevice] = []
        for name in names:
            m = _VFIO_RE.match(name)
            if not m:
                continue
            path = os.path.join(vfio_dir, name)
            try:
                major, minor, is_char = stat_device_numbers(path)
            except OSError:
                continue
            if not is_char:
                continue
            group = int(m.group(1))
            if not self._vfio_group_is_tpu(group):
                logger.debug("vfio group %d is not a Google TPU; skipped",
                             group)
                continue
            devices.append(TpuDevice(
                index=group, device_path=path, major=major, minor=minor,
                uuid=self._vfio_uuid(group),
                node_rel_path=f"{VFIO_SUBDIR}/{name}",
                companions=list(companions)))
        return devices


class FakeDeviceBackend(DeviceBackend):
    """Fake chip inventory over a plain directory (BASELINE config 1).

    Layout: <dir>/accelN are the "device nodes". When the process has
    CAP_MKNOD they are real char devices cloned from /dev/null's rdev so the
    whole mount path (cgroup grant + mknod into the container) is exercised
    for real; otherwise regular files with pseudo major:minor recorded in
    <dir>/meta.json so enumeration logic still runs everywhere.
    """

    META = "meta.json"

    def __init__(self, root: str):
        self.root = root

    @classmethod
    def create(cls, root: str, count: int) -> "FakeDeviceBackend":
        os.makedirs(root, exist_ok=True)
        meta: dict[str, dict] = {}
        null_rdev = None
        try:
            st = os.stat("/dev/null")
            if statmod.S_ISCHR(st.st_mode):
                null_rdev = st.st_rdev
        except OSError:
            pass
        for i in range(count):
            path = os.path.join(root, f"accel{i}")
            if os.path.exists(path):
                continue
            made = False
            if null_rdev is not None:
                try:
                    os.mknod(path, 0o666 | statmod.S_IFCHR, null_rdev)
                    made = True
                except (OSError, PermissionError):
                    made = False
            if not made:
                with open(path, "w"):
                    pass
                meta[f"accel{i}"] = {"major": 1, "minor": 100 + i}
        if meta:
            meta_path = os.path.join(root, cls.META)
            existing = {}
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    existing = json.load(f)
            existing.update(meta)
            with open(meta_path, "w") as f:
                json.dump(existing, f)
        return cls(root)

    @classmethod
    def create_vfio(cls, root: str, count: int) -> "FakeDeviceBackend":
        """Fake vfio layout: <root>/vfio/{0..count-1} group nodes + the
        shared <root>/vfio/vfio container node."""
        vfio_dir = os.path.join(root, VFIO_SUBDIR)
        os.makedirs(vfio_dir, exist_ok=True)
        meta: dict[str, dict] = {}
        names = [VFIO_CONTAINER] + [str(i) for i in range(count)]
        for i, name in enumerate(names):
            path = os.path.join(vfio_dir, name)
            if not os.path.exists(path):
                with open(path, "w"):
                    pass
            # container node gets its own pseudo numbers, groups follow
            meta[f"{VFIO_SUBDIR}/{name}"] = {"major": 10,
                                             "minor": 196 + i}
        meta_path = os.path.join(root, cls.META)
        existing = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                existing = json.load(f)
        existing.update(meta)
        with open(meta_path, "w") as f:
            json.dump(existing, f)
        return cls(root)

    def _meta(self) -> dict:
        path = os.path.join(self.root, self.META)
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        return {}

    def _fake_numbers(self, meta: dict, rel: str, path: str,
                      default_minor: int) -> tuple[int, int] | None:
        """(major, minor) for a fake node: stat for real chardevs, meta
        for regular-file stand-ins; None for a non-node."""
        try:
            major, minor, is_char = stat_device_numbers(path)
        except OSError:
            return None
        if is_char:
            return major, minor
        fake = meta.get(rel, {})
        return fake.get("major", 1), fake.get("minor", default_minor)

    def list_devices(self) -> list[TpuDevice]:
        meta = self._meta()
        devices = []
        try:
            names = sorted(os.listdir(self.root))
        except FileNotFoundError:
            return []
        for name in names:
            m = _ACCEL_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.root, name)
            index = int(m.group(1))
            numbers = self._fake_numbers(meta, name, path, 100 + index)
            if numbers is None:
                continue
            devices.append(TpuDevice(
                index=index, device_path=path, major=numbers[0],
                minor=numbers[1], uuid=f"tpu-fake-accel{index}"))
        if not devices:  # same accel-xor-vfio gate as the real backend
            devices.extend(self._list_fake_vfio(meta))
        devices.sort(key=lambda d: d.index)
        return devices

    def _list_fake_vfio(self, meta: dict) -> list[TpuDevice]:
        vfio_dir = os.path.join(self.root, VFIO_SUBDIR)
        try:
            names = sorted(os.listdir(vfio_dir))
        except OSError:
            return []
        companions: list[CompanionNode] = []
        container_rel = f"{VFIO_SUBDIR}/{VFIO_CONTAINER}"
        container_path = os.path.join(vfio_dir, VFIO_CONTAINER)
        if os.path.exists(container_path):
            numbers = self._fake_numbers(meta, container_rel,
                                         container_path, 196)
            if numbers is not None:
                companions = [CompanionNode(rel_path=container_rel,
                                            major=numbers[0],
                                            minor=numbers[1])]
        devices = []
        for name in names:
            m = _VFIO_RE.match(name)
            if not m:
                continue
            rel = f"{VFIO_SUBDIR}/{name}"
            path = os.path.join(vfio_dir, name)
            group = int(m.group(1))
            numbers = self._fake_numbers(meta, rel, path, 197 + group)
            if numbers is None:
                continue
            devices.append(TpuDevice(
                index=group, device_path=path, major=numbers[0],
                minor=numbers[1], uuid=f"tpu-fake-vfio{group}",
                node_rel_path=rel, companions=list(companions)))
        return devices

    def running_pids(self, device: TpuDevice) -> list[int]:
        # Fake devices cloned from /dev/null share its rdev; rdev matching
        # would report every process holding /dev/null. Match by path only.
        return scan_proc_for_device(None, None, path_hint=device.device_path)

    def mark_dead(self, rel: str, dead: bool = True) -> None:
        """Fault injection: flag a fake node (e.g. "accel2") dead so
        probe_device reports it unhealthy without disturbing enumeration
        (a dead real chip usually still has its /dev node)."""
        meta_path = os.path.join(self.root, self.META)
        meta = self._meta()
        meta.setdefault(rel, {})["dead"] = dead
        with open(meta_path, "w") as f:
            json.dump(meta, f)

    def probe_device(self, device: TpuDevice) -> tuple[bool, str]:
        rel = os.path.relpath(device.device_path, self.root)
        if self._meta().get(rel, {}).get("dead"):
            return False, "chip marked dead (fault injection)"
        if not os.path.exists(device.device_path):
            return False, "device node missing"
        return True, ""


def scan_proc_for_device(major: int | None, minor: int | None,
                         path_hint: str = "", proc_root: str = "/proc") -> list[int]:
    """PIDs with an open fd on the given device (by rdev and/or path).

    Uses the native scanner (native/tpumounter_native.cpp) when built —
    this sits on the busy-check hot path of every unmount — with this
    Python implementation as the always-available fallback. Matching by
    st_rdev catches the device regardless of the path the opener used
    (bind mounts, different mount namespaces).
    """
    from gpumounter_tpu import native as native_mod
    native_pids = native_mod.scan_device_holders(major, minor, path_hint,
                                                 proc_root)
    if native_pids is not None:
        return native_pids
    pids: list[int] = []
    want_rdev = None
    if major is not None and minor is not None and (major, minor) != (0, 0):
        want_rdev = os.makedev(major, minor)
    try:
        entries = os.listdir(proc_root)
    except FileNotFoundError:
        return []
    for entry in entries:
        if not entry.isdigit():
            continue
        fd_dir = os.path.join(proc_root, entry, "fd")
        try:
            fds = os.listdir(fd_dir)
        except OSError:
            continue
        for fd in fds:
            fd_path = os.path.join(fd_dir, fd)
            matched = False
            if want_rdev is not None:
                try:
                    st = os.stat(fd_path)
                    if statmod.S_ISCHR(st.st_mode) and st.st_rdev == want_rdev:
                        matched = True
                except OSError:
                    pass
            if not matched and path_hint:
                try:
                    if os.readlink(fd_path) == path_hint:
                        matched = True
                except OSError:
                    pass
            if matched:
                pids.append(int(entry))
                break
    return pids


def backend_from_config(cfg=None) -> DeviceBackend:
    from gpumounter_tpu.config import get_config
    cfg = cfg or get_config()
    if cfg.fake_device_dir:
        return FakeDeviceBackend(cfg.fake_device_dir)
    return RealAccelBackend(cfg.device_dir)
