from gpumounter_tpu.device.tpu import (
    TPU_ALLOCATED_STATE,
    TPU_FREE_STATE,
    TpuDevice,
)
from gpumounter_tpu.device.backend import (
    DeviceBackend,
    FakeDeviceBackend,
    RealAccelBackend,
    backend_from_config,
)

__all__ = [
    "TPU_ALLOCATED_STATE",
    "TPU_FREE_STATE",
    "TpuDevice",
    "DeviceBackend",
    "FakeDeviceBackend",
    "RealAccelBackend",
    "backend_from_config",
]
