"""TPU device model.

Replaces the reference's NVIDIA device model (pkg/device/nvidia.go:10-41):

  NvidiaGPU{MinorNumber, DeviceFilePath, UUID, State, PodName, Namespace}
  with hardcoded major 195, perm "rw", file mode "666", prefix /dev/nvidia.

TPU-native differences:
  * No hardcoded major. TPU accel-class chardevs get dynamically assigned
    majors, so major:minor always comes from stat(2) on the device node
    (SURVEY.md §2a).
  * Identity ("uuid") is the stable chip identifier derived from the sysfs
    PCI address (/sys/class/accel/accelN/device -> 0000:xx:yy.z), falling
    back to the device path. The GKE TPU device plugin advertises device IDs
    that embed the chip index, so we also keep the bare index.
  * The busy-detection primitive (reference: NVML process lists,
    nvidia.go:58-87) is a /proc/<pid>/fd scan for open descriptors on the
    device node — see gpumounter_tpu.device.backend.
"""

from __future__ import annotations

import os
import stat as statmod
from dataclasses import dataclass, field

TPU_FREE_STATE = "TPU_FREE_STATE"            # reference: GPU_FREE_STATE (nvidia.go:21)
TPU_ALLOCATED_STATE = "TPU_ALLOCATED_STATE"  # reference: GPU_ALLOCATED_STATE (nvidia.go:22)

# cgroup device-permission string; reference uses "rw" (nvidia.go:38).
DEVICE_CGROUP_PERMISSION = "rw"
# mknod file mode; reference uses "666" (nvidia.go:39).
DEVICE_FILE_MODE = 0o666


@dataclass(frozen=True)
class CompanionNode:
    """A device node that must travel with the chip into the container.

    vfio-based TPU VMs: each chip is an IOMMU group node /dev/vfio/<N>,
    and opening it is useless without the shared vfio *container* node
    /dev/vfio/vfio — so the container node rides along on every mount.
    Shared across chips: injected idempotently, never removed on unmount
    (alone it grants nothing), and its cgroup rule lives with each chip's
    grant so revoking one chip cannot break another's companion access.
    """
    rel_path: str              # path relative to /dev, e.g. "vfio/vfio"
    major: int
    minor: int


@dataclass
class TpuDevice:
    index: int                 # chip index (accelN / vfio group number)
    device_path: str           # e.g. /dev/accel0, /dev/vfio/3 (or fake path)
    major: int                 # from stat(2), never hardcoded
    minor: int
    uuid: str                  # stable id: PCI address or fallback
    state: str = TPU_FREE_STATE
    pod_name: str = ""
    namespace: str = ""
    # Node path relative to the /dev root ("accel0", "vfio/3"); defaults to
    # the basename for flat accel-class nodes.
    node_rel_path: str = ""
    companions: list[CompanionNode] = field(default_factory=list)

    @property
    def basename(self) -> str:
        return os.path.basename(self.device_path)

    @property
    def rel_path(self) -> str:
        return self.node_rel_path or self.basename

    def reset_state(self) -> None:
        # Reference: ResetState (nvidia.go:50-55)
        self.state = TPU_FREE_STATE
        self.pod_name = ""
        self.namespace = ""

    def mark_allocated(self, pod_name: str, namespace: str) -> None:
        self.state = TPU_ALLOCATED_STATE
        self.pod_name = pod_name
        self.namespace = namespace

    def __str__(self) -> str:
        return (f"TPU{self.index}[{self.uuid}] {self.device_path} "
                f"{self.major}:{self.minor} {self.state}"
                + (f" -> {self.namespace}/{self.pod_name}" if self.pod_name else ""))


def stat_device_numbers(path: str) -> tuple[int, int, bool]:
    """(major, minor, is_char_device) for a filesystem node."""
    st = os.stat(path)
    is_char = statmod.S_ISCHR(st.st_mode)
    rdev = st.st_rdev if is_char else 0
    return os.major(rdev), os.minor(rdev), is_char
