"""Defrag controller: plan, gate, and drive capacity-recovery moves.

Master-side, wired by MasterApp after the recovery controller. The
controller owns the policy seams the planner deliberately does not:

  * gating — a plan is never computed or continued while the k8s API
    is degraded (`ApiHealth`) or while the `tenant-migration-downtime`
    or `slice-feasibility` SLOs are burning (a defragmenter that adds
    migration downtime while migration downtime is the problem would
    be the outage's accelerant),
  * shard awareness — a sharded replica only plans over nodes it owns
    (the capacity view already covers exactly those), and every move's
    fencing epoch rides the migration machine's own stamps,
  * execution — each move is a real live migration with the v2
    checkpoint-assisted drain (begin(checkpoint=True)); after every
    plan group (the barrier points) capacity is re-collected and the
    fleet fragmentation index sampled, and a completed run stamps
    `capacity.recovered` — closing the loop `capacity.reject` opened.

Destinations: a move's target pod is an operator-provisioned standby —
a Running pod on the destination node annotated
`tpumounter.io/defrag-dest` (see docs/RUNBOOK.md). No standby on the
planned node blocks that move; the controller records it and stops
rather than inventing a destination.
"""

from __future__ import annotations

import copy
import secrets
import statistics
import threading
import time
from collections import deque
from concurrent import futures

from gpumounter_tpu.config import get_config
from gpumounter_tpu.defrag.planner import PlanError, plan_moves
from gpumounter_tpu.faults import failpoints
from gpumounter_tpu.k8s.errors import is_outage
from gpumounter_tpu.k8s.types import Pod
from gpumounter_tpu.migrate.journal import migration_active
from gpumounter_tpu.obs import trace
from gpumounter_tpu.obs.audit import AUDIT
from gpumounter_tpu.obs.flight import FLIGHT
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("defrag")

#: standby destination marker (annotation; any value). Operators
#: provision warm destination pods and mark them; the controller only
#: ever mounts chips into pods that opted in.
ANNOT_DEFRAG_DEST = "tpumounter.io/defrag-dest"

#: SLO objectives whose burn parks the controller (never start or
#: continue a plan while either is burning).
GATING_OBJECTIVES = ("tenant-migration-downtime", "slice-feasibility")

DEFRAG_PLANS = REGISTRY.counter(
    "tpumounter_defrag_plans_total",
    "Defrag plans computed (including empty no-op plans)")
DEFRAG_MOVES = REGISTRY.counter(
    "tpumounter_defrag_moves_total",
    "Defrag moves executed, by migration outcome")
DEFRAG_REFUSALS = REGISTRY.counter(
    "tpumounter_defrag_refusals_total",
    "Plans/runs refused, by bounded cause vocabulary")
DEFRAG_RUNNING = REGISTRY.gauge(
    "tpumounter_defrag_running",
    "1 while a defrag run is executing moves")


class DefragRefused(Exception):
    """Gate or staleness refusal; maps to an HTTP status. The bounded
    `cause` vocabulary: stale-snapshot | slo-burn | api-degraded |
    no-plan | busy."""

    def __init__(self, message: str, cause: str, status: int = 409):
        super().__init__(message)
        self.cause = cause
        self.status = status


class DefragController:
    """One per master process; all state in memory (a restarted master
    re-plans from fresh capacity — plans are cheap and deliberately
    not durable, unlike the per-move migration journals, which crash-
    recover through the migration machine itself)."""

    def __init__(self, kube, migrations, capacity, fleet, slo=None,
                 apihealth=None, shards=None, cfg=None, health=None):
        self.cfg = cfg or get_config()
        self.kube = kube
        self.migrations = migrations
        self.capacity = capacity
        self.fleet = fleet
        self.slo = slo
        self.apihealth = apihealth
        self.shards = shards
        #: optional HealthPlane: quarantined hosts are non-destinations
        #: for every planned move (excluded_hosts degrades to the empty
        #: set, so a broken health plane never blocks planning).
        self.health = health
        self._lock = OrderedLock("defrag.state")
        self._plan: dict | None = None
        self._run: dict | None = None          # the in-flight run view
        self._history: deque[dict] = deque(maxlen=32)
        self._pause = threading.Event()
        self._run_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None

    # --- gates ---

    def _gate_state(self) -> dict:
        burning = []
        if self.slo is not None:
            try:
                evaluation = self.slo.evaluate()
            except Exception as exc:  # noqa: BLE001 — a broken SLO
                # engine reads as burning: fail closed, the defragmenter
                # is optional capacity recovery, not a liveness path
                logger.warning("slo evaluation for defrag gate failed: "
                               "%s", exc)
                burning = ["slo-engine-error"]
            else:
                threshold = float(evaluation.get("burn_threshold", 2.0))
                for objective in evaluation.get("objectives", []):
                    if objective.get("name") not in GATING_OBJECTIVES:
                        continue
                    if objective.get("breached") or \
                            float(objective.get("burn_fast", 0.0)) \
                            >= threshold:
                        burning.append(objective["name"])
        api_ok = self.apihealth is None or self.apihealth.ok()
        return {"api_ok": api_ok,
                "api_state": (self.apihealth.state()
                              if self.apihealth is not None else "ok"),
                "slo_burning": burning}

    def _check_gates(self, action: str) -> dict:
        gates = self._gate_state()
        if not gates["api_ok"]:
            self._refuse(action, "api-degraded",
                         f"k8s api is {gates['api_state']}; the "
                         f"defragmenter parks until it heals", 503)
        if gates["slo_burning"]:
            self._refuse(action, "slo-burn",
                         f"SLO burning: {', '.join(gates['slo_burning'])}"
                         f"; refusing to add migration disruption on "
                         f"top of it", 503)
        return gates

    def _refuse(self, action: str, cause: str, message: str,
                status: int = 409) -> None:
        DEFRAG_REFUSALS.inc(outcome=cause)
        AUDIT.record(f"defrag.{action}", actor="defrag-controller",
                     outcome=f"refused: {cause}", cause=cause,
                     detail=message)
        raise DefragRefused(message, cause, status)

    # --- cost model ---

    def _cost_fn(self):
        """Per-tenant move-cost estimator from REAL migration history:
        the journals' terminal stamps carry per-phase wall times (the
        satellite contract in migrate/orchestrator.py), summed per
        tenant; the fleet median covers tenants that never moved; the
        assembled trace (obs/assembly.py) backfills journals that
        predate the phase stamps. Seconds per move, scaled per chip for
        tenants with a different chip count than their history."""
        per_tenant: dict[str, float] = {}
        totals: list[float] = []
        try:
            journals = self.migrations.list_migrations()
        except Exception as exc:  # noqa: BLE001 — cost model degrades
            # to the flat default; planning must not fail on history
            logger.warning("migration history for cost model "
                           "unavailable: %s", exc)
            journals = []
        for journal in journals:
            if journal.get("outcome") != "succeeded":
                continue
            durations = journal.get("phase_durations_s") or {}
            total = sum(float(v) for v in durations.values())
            if not total and journal.get("trace_id"):
                try:
                    from gpumounter_tpu.obs import assembly
                    assembled = assembly.assemble(journal["trace_id"])
                    if assembled:
                        total = float(
                            assembled.get("wall_ms", 0.0)) / 1000.0
                except Exception:  # noqa: BLE001 — backfill only
                    total = 0.0
            if not total:
                continue
            src = journal.get("source") or {}
            tenant = f"{src.get('namespace')}/{src.get('pod')}"
            per_tenant[tenant] = total
            totals.append(total)
        fleet_median = statistics.median(totals) if totals else 1.0

        def cost(tenant: str, n_chips: int) -> float:
            base = per_tenant.get(tenant)
            if base is not None:
                return base
            return fleet_median * max(1, n_chips)
        return cost

    def _resolve_tenants(self, nodes: dict) -> dict:
        """Capacity's per-chip `held` map names the BOOKING holder —
        for chips mounted through slave pods that is the tpu-pool
        slave, not the tenant. Rewrite every holder to its owner pod
        (the slave's tpumounter.io/owner[-namespace] annotations,
        allocator/allocator.py) so plans, per-tenant budgets and the
        cost model all speak in tenants the migration machine can
        actually move. A holder that cannot be resolved stays as-is:
        the planner will price and budget it under the booking name,
        and the move fails loudly at admission instead of silently."""
        cache: dict[str, str] = {}
        resolved: dict[str, dict] = {}
        for node, entry in nodes.items():
            cap = entry.get("capacity") \
                if isinstance(entry, dict) else None
            if not isinstance(cap, dict) \
                    or not isinstance(cap.get("held"), dict):
                resolved[node] = entry
                continue
            entry = dict(entry)
            entry["capacity"] = dict(cap)
            entry["capacity"]["held"] = {
                index: self._owner_of(holder, cache)
                for index, holder in cap["held"].items()}
            resolved[node] = entry
        return resolved

    def _owner_of(self, holder, cache: dict[str, str]) -> str:
        holder = str(holder)
        if holder in cache:
            return cache[holder]
        namespace, _, pod_name = holder.partition("/")
        owner = holder
        try:
            pod = Pod(self.kube.get_pod(namespace, pod_name))
        except Exception as exc:  # noqa: BLE001 — triage, then keep
            # the booking name (admission is the loud failure path)
            (logger.debug if is_outage(exc) else logger.info)(
                "holder %s unresolvable (%s); planning against the "
                "booking name", holder, exc)
        else:
            owner_ns = pod.annotations.get(
                "tpumounter.io/owner-namespace")
            owner_name = pod.annotations.get("tpumounter.io/owner")
            if owner_ns and owner_name:
                owner = f"{owner_ns}/{owner_name}"
        cache[holder] = owner
        return owner

    # --- planning ---

    def plan(self, target_block: int | None = None) -> dict:
        """Compute and adopt a plan from a FRESH capacity snapshot.
        Raises DefragRefused on any gate; a no-move plan is returned,
        not raised (nothing blocked is a fine fleet state)."""
        with trace.span("defrag.plan"):
            self._check_gates("plan")
            target = int(target_block or self.cfg.defrag_target_block)
            max_age = float(self.cfg.defrag_snapshot_max_age_s)
            try:
                rollup = self.fleet.payload(max_age_s=max_age)
            except Exception as exc:  # noqa: BLE001 — collection
                # failure means NO trustworthy snapshot: refuse like a
                # stale one (same contract), louder when it was an
                # outage (the api gate will catch the next attempt)
                self._refuse(
                    "plan", "stale-snapshot",
                    f"capacity collection failed "
                    f"({'api outage' if is_outage(exc) else exc}); "
                    f"refusing to plan blind", 503)
            nodes = rollup.get("nodes") or {}
            if self.shards is not None and self.shards.active():
                nodes = {n: e for n, e in nodes.items()
                         if self.shards.owns_node(n)}
            nodes = self._resolve_tenants(nodes)
            try:
                plan = plan_moves(
                    nodes,
                    target_block=target,
                    max_moves=int(self.cfg.defrag_max_moves),
                    tenant_move_budget=int(
                        self.cfg.defrag_tenant_move_budget),
                    snapshot_at=rollup.get("at"),
                    max_snapshot_age_s=max_age,
                    now=time.time(),
                    non_destinations=(
                        self.health.excluded_hosts()
                        if self.health is not None else frozenset()),
                    cost_fn=self._cost_fn())
            except PlanError as exc:
                self._refuse("plan", exc.cause, str(exc), exc.status)
            plan["id"] = f"dfp-{secrets.token_hex(4)}"
            plan["created_at"] = time.time()
            if self.shards is not None and self.shards.active():
                # epoch stamp per source node: an operator reading the
                # plan can tell which fencing epoch its moves will carry
                plan["epochs"] = {
                    m["source_node"]:
                        self.shards.node_epoch(m["source_node"])
                    for m in plan["moves"]}
            with self._lock:
                self._plan = plan
            DEFRAG_PLANS.inc()
            summary = (f"defrag plan {plan['id']}: {len(plan['moves'])} "
                       f"move(s) over {len(plan['groups'])} host(s), "
                       f"fragmentation {plan['fragmentation_before']} "
                       f"-> {plan['fragmentation_after']} (predicted)")
            AUDIT.record("defrag.plan", actor="defrag-controller",
                         outcome=f"planned: {len(plan['moves'])} move(s)",
                         plan_id=plan["id"], moves=len(plan["moves"]),
                         target_block=target,
                         fragmentation_before=plan["fragmentation_before"],
                         fragmentation_after=plan["fragmentation_after"])
            FLIGHT.record("marker", summary, plan_id=plan["id"])
            logger.info("%s", summary)
            return copy.deepcopy(plan)

    # --- running ---

    def run(self, plan_id: str | None = None,
            wait: bool = False) -> dict:
        """Execute the adopted plan (optionally checked against
        `plan_id`). Gates re-checked now AND before every group. The
        moves run on a background thread unless wait=True (tests, the
        background loop)."""
        self._check_gates("run")
        with self._lock:
            if self._run_thread is not None \
                    and self._run_thread.is_alive():
                self._refuse("run", "busy",
                             "a defrag run is already executing", 409)
            plan = self._plan
            if plan is None:
                self._refuse("run", "no-plan",
                             "no adopted plan; POST /defrag/plan first",
                             409)
            if plan_id is not None and plan["id"] != plan_id:
                self._refuse("run", "no-plan",
                             f"adopted plan is {plan['id']}, not "
                             f"{plan_id}", 409)
            age = time.time() - float(plan["created_at"])
            if age > float(self.cfg.defrag_snapshot_max_age_s):
                self._plan = None
                bound = float(self.cfg.defrag_snapshot_max_age_s)
                self._refuse("run", "stale-snapshot",
                             f"plan {plan['id']} is {age:.1f}s old "
                             f"(bound {bound:.0f}s); the fleet has "
                             f"moved on — re-plan", 409)
            plan = copy.deepcopy(plan)
            self._pause.clear()
            run = {"plan_id": plan["id"], "status": "running",
                   "started_at": time.time(), "moves": [],
                   "barriers": [], "trace_id": None}
            self._run = run
        if wait:
            self._execute(plan)
            return self.payload()
        thread = threading.Thread(target=self._execute, args=(plan,),
                                  name=f"defrag-{plan['id']}",
                                  daemon=True)
        with self._lock:
            self._run_thread = thread
        thread.start()
        return self.payload()

    def pause(self) -> dict:
        """Stop after the in-flight move; idempotent, also clears an
        adopted-but-unstarted plan from consideration."""
        self._pause.set()
        AUDIT.record("defrag.pause", actor="defrag-controller",
                     outcome="pause requested")
        return self.payload()

    def _barrier(self, run: dict, label: str) -> float | None:
        """Re-collect capacity and sample the fleet fragmentation index
        — one of the plan's barrier points (chaos invariant 18 asserts
        the samples are monotonically non-increasing)."""
        try:
            payload = self.capacity.payload(max_age_s=0.0)
            index = float(payload["fleet"]["fragmentation_index"])
        except Exception as exc:  # noqa: BLE001 — a failed sample is
            # recorded as such; the run's verdict does not depend on it
            logger.warning("defrag barrier capacity sample failed: %s",
                           exc)
            run["barriers"].append({"label": label, "error": str(exc)})
            return None
        run["barriers"].append({"label": label, "at": time.time(),
                                "fragmentation_index": index})
        FLIGHT.record("marker",
                      f"defrag {run['plan_id']} barrier {label}: "
                      f"fragmentation index {index}",
                      plan_id=run["plan_id"])
        return index

    def _execute(self, plan: dict) -> None:
        with trace.span("defrag.run", plan_id=plan["id"]):
            self._execute_traced(plan)

    def _execute_traced(self, plan: dict) -> None:
        run = self._run
        run["trace_id"] = trace.current_trace_id()
        DEFRAG_RUNNING.set(1.0)
        frag_before = None
        succeeded = 0
        try:
            failpoints.fire("defrag.run", plan_id=plan["id"])
            frag_before = self._barrier(run, "start")
            by_group: dict[str, list[dict]] = {}
            for move in plan["moves"]:
                by_group.setdefault(move["group"], []).append(move)
            # Cross-host group parallelism: consecutive groups whose
            # host sets (source node + every move's destination) are
            # pairwise disjoint share no chips, no standby pods and no
            # kubelet, so their moves cannot conflict — they execute
            # concurrently, bounded by defrag_group_fanout (1 = the
            # serial shape). Gates and pause are re-checked between
            # BATCHES, and the barrier samples land after a batch
            # completes — the fleet state they sample is quiescent, so
            # chaos invariant 18 (monotonically non-increasing
            # fragmentation at barriers) holds unchanged.
            batches = self._disjoint_batches(plan["groups"], by_group)
            aborted = False
            for batch in batches:
                if self._pause.is_set():
                    run["status"] = "paused"
                    aborted = True
                    break
                gates = self._gate_state()
                if not gates["api_ok"]:
                    run["status"] = "parked-api"
                    run["parked"] = gates["api_state"]
                    DEFRAG_REFUSALS.inc(outcome="api-degraded")
                    aborted = True
                    break
                if gates["slo_burning"]:
                    run["status"] = "parked-slo"
                    run["parked"] = gates["slo_burning"]
                    DEFRAG_REFUSALS.inc(outcome="slo-burn")
                    aborted = True
                    break
                batch_ok, batch_succeeded = self._run_batch(
                    run, batch, by_group)
                succeeded += batch_succeeded
                for group in batch:
                    self._barrier(run, group["node"])
                if not batch_ok:
                    run["status"] = "failed-move"
                    aborted = True
                    break
            if not aborted and run["status"] == "running":
                run["status"] = "completed"
        except Exception as exc:  # noqa: BLE001 — terminal boundary:
            # the run view must reach history with a truthful status
            logger.exception("defrag run %s died: %s", plan["id"], exc)
            run["status"] = "failed"
            run["error"] = f"{type(exc).__name__}: {exc}"
        finally:
            DEFRAG_RUNNING.set(0.0)
            run["finished_at"] = time.time()
            frag_after = self._barrier(run, "end")
            if succeeded and frag_before is not None \
                    and frag_after is not None:
                # the capacity-plane follow-through: the re-collect
                # above refreshed the rollup; stamp what the run
                # bought back (mirrored onto the flight recorder by
                # the audit subscriber)
                self.capacity.record_recovery(
                    cause="defrag", plan_id=plan["id"],
                    fragmentation_before=frag_before,
                    fragmentation_after=frag_after,
                    moves=succeeded)
            AUDIT.record(
                "defrag.run", actor="defrag-controller",
                outcome=f"{run['status']}: {succeeded}/"
                        f"{len(plan['moves'])} move(s)",
                plan_id=plan["id"], status=run["status"],
                moves_succeeded=succeeded,
                moves_planned=len(plan["moves"]))
            FLIGHT.record("marker",
                          f"defrag run {plan['id']} {run['status']}: "
                          f"{succeeded}/{len(plan['moves'])} move(s)",
                          plan_id=plan["id"])
            with self._lock:
                self._history.append(copy.deepcopy(run))
                self._run = None
                self._run_thread = None
                if self._plan is not None \
                        and self._plan["id"] == plan["id"]:
                    self._plan = None  # consumed, even on failure

    def _disjoint_batches(self, groups: list[dict],
                          by_group: dict[str, list[dict]],
                          ) -> list[list[dict]]:
        """Partition the plan's groups, in order, into batches of
        consecutive groups with pairwise-disjoint host footprints
        (source node plus every move's destination node), capped at
        cfg.defrag_group_fanout. Order-preserving on purpose: the
        planner ranks groups by recovery value, and a reordering
        "optimization" here would quietly change which hosts recover
        first."""
        fanout = max(1, int(getattr(self.cfg, "defrag_group_fanout", 1)))

        def hosts_of(group: dict) -> set[str]:
            hosts = {group["node"]}
            for move in by_group.get(group["node"], []):
                hosts.add(move["source_node"])
                hosts.add(move["dest_node"])
            return hosts

        batches: list[list[dict]] = []
        batch: list[dict] = []
        batch_hosts: set[str] = set()
        for group in groups:
            hosts = hosts_of(group)
            if batch and (len(batch) >= fanout
                          or batch_hosts & hosts):
                batches.append(batch)
                batch, batch_hosts = [], set()
            batch.append(group)
            batch_hosts |= hosts
        if batch:
            batches.append(batch)
        return batches

    def _run_batch(self, run: dict, batch: list[dict],
                   by_group: dict[str, list[dict]],
                   ) -> tuple[bool, int]:
        """Execute one batch of host-disjoint groups — concurrently
        when the batch has more than one. Moves WITHIN a group stay
        serial (they share the source host's kubelet and standby
        pool). Returns (every move succeeded, succeeded count)."""

        def run_group(group: dict) -> tuple[bool, int]:
            ok, done = True, 0
            for move in by_group.get(group["node"], []):
                if self._execute_move(run, move) == "succeeded":
                    done += 1
                else:
                    ok = False
                    break
            return ok, done

        if len(batch) == 1:
            return run_group(batch[0])
        ctx = trace.current()

        def traced(group: dict) -> tuple[bool, int]:
            # Contextvars don't cross threads: re-attach the run's
            # trace so each move's spans join the same story.
            with trace.attached(ctx):
                return run_group(group)

        with futures.ThreadPoolExecutor(
                max_workers=len(batch),
                thread_name_prefix="defrag-group") as pool:
            results = list(pool.map(traced, batch))
        return (all(ok for ok, _ in results),
                sum(done for _, done in results))

    def _execute_move(self, run: dict, move: dict) -> str:
        """One live migration with the checkpoint-assisted drain.
        Returns the migration outcome ("succeeded", ...), or a
        controller-level refusal string when no destination standby
        exists."""
        record = {"namespace": move["namespace"], "pod": move["pod"],
                  "source_node": move["source_node"],
                  "dest_node": move["dest_node"],
                  "chips": move["chips"]}
        run["moves"].append(record)
        dest = self._resolve_destination(move)
        if dest is None:
            record["outcome"] = "blocked-no-destination"
            DEFRAG_MOVES.inc(outcome="blocked")
            logger.warning(
                "defrag %s: no standby destination pod on %s (annotate "
                "a Running pod with %s); move of %s/%s blocked",
                run["plan_id"], move["dest_node"], ANNOT_DEFRAG_DEST,
                move["namespace"], move["pod"])
            return "blocked-no-destination"
        dest_ns, dest_pod = dest
        record["dest_pod"] = f"{dest_ns}/{dest_pod}"
        try:
            journal = self.migrations.begin(
                move["namespace"], move["pod"], dest_ns, dest_pod,
                checkpoint=True)
        except Exception as exc:  # noqa: BLE001 — admission boundary
            record["outcome"] = "rejected"
            record["error"] = str(exc)
            DEFRAG_MOVES.inc(outcome="rejected")
            return "rejected"
        record["migration_id"] = journal["id"]
        record["trace_id"] = journal.get("trace_id")
        final = self.migrations.wait(journal["id"], timeout_s=600.0)
        outcome = (final or {}).get("outcome") or "unknown"
        record["outcome"] = outcome
        if final is not None:
            record["downtime_s"] = final.get("downtime_s")
            record["phases"] = final.get("phase_durations_s")
            record["checkpointed"] = final.get("checkpointed")
        DEFRAG_MOVES.inc(outcome=outcome)
        return outcome

    def _resolve_destination(self, move: dict) -> tuple[str, str] | None:
        """Find an operator-provisioned standby on the destination node:
        Running, annotated tpumounter.io/defrag-dest, same namespace as
        the tenant, not already part of a migration. Deterministic pick
        (sorted by name) so a re-run converges on the same standby."""
        try:
            listed = self.kube.list_pods(move["namespace"])
        except Exception as exc:  # noqa: BLE001 — treated as no
            # destination; the run stops instead of guessing (an outage
            # here would fail the migration admission anyway)
            logger.warning("standby listing on %s failed (%s): %s",
                           move["dest_node"],
                           "api outage" if is_outage(exc) else "api "
                           "error", exc)
            return None
        candidates = []
        for raw in listed:
            pod = Pod(raw)
            if pod.node_name != move["dest_node"]:
                continue
            if ANNOT_DEFRAG_DEST not in pod.annotations:
                continue
            if pod.name == move["pod"] \
                    and pod.namespace == move["namespace"]:
                continue
            if (pod.phase or "").lower() != "running":
                continue
            if migration_active(pod.annotations):
                continue
            candidates.append((pod.namespace, pod.name))
        return min(candidates) if candidates else None

    # --- surfaces ---

    def payload(self) -> dict:
        """The GET /defrag response."""
        gates = self._gate_state()
        with self._lock:
            plan = copy.deepcopy(self._plan)
            run = copy.deepcopy(self._run)
            history = [copy.deepcopy(r) for r in self._history]
        return {
            "at": round(time.time(), 3),
            "enabled": bool(self.cfg.defrag_enabled),
            "gates": gates,
            "plan": plan,
            "run": run,
            "history": history[-8:],
        }

    # --- background loop (opt-in via defrag_enabled) ---

    def start(self) -> None:
        if self._loop_thread is not None:
            return
        self._stop.clear()
        self._loop_thread = threading.Thread(
            target=self._loop, name="defrag-loop", daemon=True)
        self._loop_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._pause.set()
        thread, runner = self._loop_thread, self._run_thread
        if thread is not None:
            thread.join(timeout=5.0)
        if runner is not None:
            runner.join(timeout=5.0)
        self._loop_thread = None

    def _loop(self) -> None:
        while not self._stop.wait(float(self.cfg.defrag_interval_s)):
            try:
                plan = self.plan()
                if plan["moves"]:
                    self.run(plan["id"], wait=True)
            except DefragRefused as exc:
                logger.info("defrag background pass parked: %s (%s)",
                            exc, exc.cause)
            except Exception as exc:  # noqa: BLE001 — the loop is the
                # capacity-recovery heartbeat; one bad pass must not
                # kill it
                logger.exception("defrag background pass failed: %s",
                                 exc)
