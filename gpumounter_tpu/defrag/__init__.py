"""ICI defragmenter: capacity recovery through live migration.

PR 14's capacity plane (obs/capacity.py) can *say* a slice shape is
`admissible-after-defrag`; this package is the subsystem that acts on
the verdict. The planner (planner.py) turns a capacity snapshot into a
minimal-cost sequence of tenant moves, the controller (controller.py)
executes it through the live-migration machine (migrate/orchestrator.py)
with the v2 checkpoint-assisted drain, hard-gated on tenant-SLO burn and
ApiHealth, and closes the loop with a `capacity.recovered` audit stamp.
"""

from gpumounter_tpu.defrag.controller import (
    ANNOT_DEFRAG_DEST,
    DefragController,
    DefragRefused,
)
from gpumounter_tpu.defrag.planner import (
    PlanError,
    fleet_fragmentation_index,
    parse_hosts,
    plan_moves,
)

__all__ = [
    "ANNOT_DEFRAG_DEST",
    "DefragController",
    "DefragRefused",
    "PlanError",
    "fleet_fragmentation_index",
    "parse_hosts",
    "plan_moves",
]
