"""Defragmentation planner: capacity snapshot -> minimal-cost move plan.

Pure logic, no I/O: the controller feeds it the fleet collector's raw
node entries (the same `capacity` inventory sections obs/capacity.py
derives its views from) and gets back a JSON-able plan. Keeping the
planner side-effect free is what makes the negative control enforceable:
a stale snapshot is refused HERE, by construction, before anything can
act on it.

The unit of work is a *group*: the set of moves that flips one blocked
host's verdict (free chips become one ICI-connected block of the target
size). Groups are the plan's barrier points — the controller re-collects
capacity after each one and the chaos harness asserts the fleet
fragmentation index is monotonically non-increasing across them, which
the planner guarantees by simulation: a group whose predicted post-state
raises the index is dropped, not scheduled.

Constraints (config.py `defrag_*`):
  * at most `max_moves` tenant migrations per plan,
  * no tenant moved more than `tenant_move_budget` times,
  * per-move cost from the caller's cost model (real per-tenant phase
    timings out of the migration journals' terminal stamps; fleet
    median as fallback) — groups are scheduled cheapest-first, so when
    the move budget bites, the budget bought the most capacity it could.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from gpumounter_tpu.allocator import placement
from gpumounter_tpu.obs.capacity import largest_ici_block
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("defrag.planner")

#: upper bound on tenants evicted per host unblock — subsets are
#: enumerated exhaustively below this size (hosts have <= 8 chips, so
#: the search space is tiny); a host needing more eviction than this is
#: reported blocked instead of swept wholesale.
MAX_EVICTIONS_PER_HOST = 3


class PlanError(Exception):
    """Planner refusal. `cause` is machine-readable and bounded:
    "stale-snapshot" is the negative-control contract (a planner fed an
    outdated capacity view must refuse, not thrash)."""

    def __init__(self, message: str, cause: str = "invalid",
                 status: int = 409):
        super().__init__(message)
        self.cause = cause
        self.status = status


@dataclass
class HostView:
    """One host's planning view, parsed from its inventory section."""

    node: str
    free: set[int] = field(default_factory=set)
    warm: set[int] = field(default_factory=set)
    fenced: set[int] = field(default_factory=set)
    held: dict[int, str] = field(default_factory=dict)  # index -> ns/pod
    stale: bool = False
    known: bool = True

    def tenants(self) -> dict[str, list[int]]:
        out: dict[str, list[int]] = {}
        for index, tenant in sorted(self.held.items()):
            out.setdefault(tenant, []).append(index)
        return out


def parse_hosts(nodes: dict[str, dict]) -> dict[str, HostView]:
    """Fleet-collector node entries -> planning views. Stale entries and
    nodes without an inventory section parse as unknown: the planner
    neither evicts from nor places onto a host it cannot see."""
    hosts: dict[str, HostView] = {}
    for node, entry in (nodes or {}).items():
        if not isinstance(entry, dict):
            continue
        raw = entry.get("capacity")
        if bool(entry.get("stale")) or not isinstance(raw, dict):
            hosts[node] = HostView(node=node, stale=bool(
                entry.get("stale")), known=False)
            continue
        held = {int(k): str(v) for k, v in (raw.get("held") or {}).items()}
        hosts[node] = HostView(
            node=node,
            free={int(i) for i in raw.get("free") or []},
            warm={int(i) for i in raw.get("warm") or []},
            fenced={int(i) for i in raw.get("fenced") or []},
            held=held,
            known=bool(raw.get("ownership_known", True)))
    return hosts


def fleet_fragmentation_index(hosts: dict[str, HostView]) -> float:
    """The capacity plane's weighted fleet index (1 - achievable/free)
    recomputed over planning views — identical math, so a predicted
    post-plan index and the /capacity payload's are comparable."""
    free = 0
    achievable = 0
    for view in hosts.values():
        if not view.known and not view.free:
            continue
        free += len(view.free)
        achievable += largest_ici_block(sorted(view.free))
    return round(1.0 - achievable / free, 4) if free else 0.0


def _blocked_hosts(hosts: dict[str, HostView],
                   target_block: int) -> list[HostView]:
    """Hosts the feasibility table would call admissible-after-defrag
    at this block size: enough reclaimable chips (free + warm), but the
    free set's largest ICI component is too small."""
    out = []
    for view in hosts.values():
        if view.stale or not view.known:
            continue
        if len(view.free) + len(view.warm) < target_block:
            continue
        if largest_ici_block(sorted(view.free)) >= target_block:
            continue
        out.append(view)
    return sorted(out, key=lambda v: v.node)


def _unblock_subset(view: HostView, target_block: int,
                    cost_fn) -> tuple[list[str], float] | None:
    """The cheapest tenant subset whose eviction makes this host's free
    set hold an ICI block of `target_block` chips. Minimality order:
    fewest moves, then lowest summed cost, then fewest chips evicted.
    Exhaustive over subsets up to MAX_EVICTIONS_PER_HOST (hosts are
    small). None when no subset within the bound works."""
    tenants = view.tenants()
    names = sorted(tenants)
    best: tuple[tuple[int, float, int], list[str]] | None = None
    for size in range(1, min(MAX_EVICTIONS_PER_HOST, len(names)) + 1):
        for combo in itertools.combinations(names, size):
            evicted = set().union(*(tenants[t] for t in combo))
            if largest_ici_block(sorted(view.free | evicted)) \
                    < target_block:
                continue
            cost = sum(cost_fn(t, len(tenants[t])) for t in combo)
            rank = (size, cost, len(evicted))
            if best is None or rank < best[0]:
                best = (rank, list(combo))
        if best is not None:
            break  # a smaller subset always beats a larger one
    if best is None:
        return None
    return best[1], best[0][1]


def _place(sim: dict[str, HostView], source: str, n_chips: int,
           avoid: set[str]) -> tuple[str, list[int]] | None:
    """Pick a destination host + chips for an evicted tenant: best-fit
    over the simulated free sets (the smallest sufficient ICI component,
    so a big contiguous block is not shredded for a small tenant), never
    a host in `avoid` (the hosts this plan is unblocking — re-fragmenting
    one would undo the plan from inside)."""
    candidates: list[tuple[int, str]] = []
    for node, view in sim.items():
        if node == source or node in avoid or view.stale or not view.known:
            continue
        block = largest_ici_block(sorted(view.free))
        if block >= n_chips:
            candidates.append((block, node))
    if not candidates:
        return None
    # best fit: smallest sufficient block; node name breaks ties for
    # deterministic plans (a re-plan over the same snapshot converges)
    candidates.sort()
    node = candidates[0][1]
    chips = placement.best_block(sorted(sim[node].free), n_chips)
    return node, chips


def plan_moves(nodes: dict[str, dict], *,
               target_block: int,
               max_moves: int,
               tenant_move_budget: int = 1,
               snapshot_at: float | None = None,
               max_snapshot_age_s: float | None = None,
               now: float | None = None,
               non_destinations: frozenset[str] | set[str] = frozenset(),
               cost_fn=None) -> dict:
    """Compute a move plan from a capacity snapshot.

    `nodes` is the fleet collector's node map (entries carrying the
    worker-reported `capacity` section). With `snapshot_at` +
    `max_snapshot_age_s` + `now` the snapshot's age is checked FIRST and
    a stale one raises PlanError("stale-snapshot") — the negative
    control. `non_destinations` (the health plane's quarantined set) are
    hosts no evicted tenant may land on — moving a tenant ONTO a limping
    node would convert fragmentation pain into gray-failure pain.
    Returns a JSON-able plan dict; `moves` empty when nothing is blocked
    (a no-op plan is a fine answer, a stale plan is not)."""
    if max_snapshot_age_s is not None and now is not None:
        if snapshot_at is None:
            raise PlanError(
                "capacity snapshot has no collection timestamp; "
                "refusing to plan against a view of unknown age",
                cause="stale-snapshot")
        age = now - float(snapshot_at)
        if age > max_snapshot_age_s:
            raise PlanError(
                f"capacity snapshot is {age:.1f}s old (bound "
                f"{max_snapshot_age_s:.0f}s); refusing to plan moves "
                f"against a stale view — re-collect and re-plan",
                cause="stale-snapshot")
    if cost_fn is None:
        def cost_fn(_tenant: str, n_chips: int) -> float:  # noqa: ANN001
            return float(n_chips)  # flat per-chip estimate
    hosts = parse_hosts(nodes)
    frag_before = fleet_fragmentation_index(hosts)
    blocked = _blocked_hosts(hosts, target_block)

    # Candidate groups, one per blocked host, cheapest-first.
    candidates: list[dict] = []
    skipped: list[dict] = []
    for view in blocked:
        found = _unblock_subset(view, target_block, cost_fn)
        if found is None:
            skipped.append({"node": view.node,
                            "reason": "no-eviction-subset"})
            continue
        tenants, cost = found
        candidates.append({"node": view.node, "tenants": tenants,
                           "est_cost_s": round(cost, 3)})
    candidates.sort(key=lambda g: (g["est_cost_s"], g["node"]))

    sim = parse_hosts(nodes)  # independent mutable copy to simulate on
    moves: list[dict] = []
    groups: list[dict] = []
    tenant_moves: dict[str, int] = {}
    frag_at_barrier = frag_before
    # Never place an evicted tenant onto ANY blocked host (not just the
    # ones already scheduled): consuming a blocked host's free chips
    # could make its own unblock — computed upfront — unachievable.
    blocked_names = {v.node for v in blocked}
    for group in candidates:
        node = group["node"]
        view = sim[node]
        tenants_here = view.tenants()
        if len(moves) + len(group["tenants"]) > max_moves:
            skipped.append({"node": node, "reason": "move-budget"})
            continue
        if any(tenant_moves.get(t, 0) + 1 > tenant_move_budget
               for t in group["tenants"]):
            skipped.append({"node": node, "reason": "tenant-budget"})
            continue
        # Tentatively place every eviction; all-or-nothing per group.
        unblocking = blocked_names | {node} | set(non_destinations)
        staged: list[dict] = []
        placed_ok = True
        snapshot = {n: (set(v.free), dict(v.held)) for n, v in sim.items()}
        for tenant in group["tenants"]:
            chips = tenants_here.get(tenant) or []
            placed = _place(sim, node, len(chips), avoid=unblocking)
            if placed is None:
                placed_ok = False
                skipped.append({"node": node, "tenant": tenant,
                                "reason": "no-destination"})
                break
            dest, dest_chips = placed
            namespace, _, pod = tenant.partition("/")
            staged.append({
                "namespace": namespace, "pod": pod,
                "source_node": node, "dest_node": dest,
                "chips": len(chips), "source_indices": sorted(chips),
                "dest_indices": sorted(dest_chips),
                "est_cost_s": round(cost_fn(tenant, len(chips)), 3),
                "group": node,
            })
            # apply to the simulation
            view.free.update(chips)
            for index in chips:
                view.held.pop(index, None)
            sim[dest].free.difference_update(dest_chips)
            for index in dest_chips:
                sim[dest].held[index] = tenant
        frag_here = fleet_fragmentation_index(sim)
        if not placed_ok or frag_here > frag_at_barrier:
            # roll the simulation back; a group that cannot fully place
            # or would RAISE the fleet index is dropped, never partially
            # scheduled (the monotonic-barrier invariant is planned-in,
            # not hoped-for)
            for n, (free, held) in snapshot.items():
                sim[n].free = free
                sim[n].held = held
            if placed_ok:
                skipped.append({"node": node,
                                "reason": "would-raise-fragmentation"})
            continue
        for staged_move in staged:
            tenant = (f"{staged_move['namespace']}/"
                      f"{staged_move['pod']}")
            tenant_moves[tenant] = tenant_moves.get(tenant, 0) + 1
        moves.extend(staged)
        groups.append({"node": node, "moves": len(staged),
                       "est_cost_s": group["est_cost_s"],
                       "predicted_fragmentation_index": frag_here})
        frag_at_barrier = frag_here

    return {
        "target_block": int(target_block),
        "snapshot_at": snapshot_at,
        "moves": moves,
        "groups": groups,
        "skipped": skipped,
        "blocked_hosts": [v.node for v in blocked],
        "fragmentation_before": frag_before,
        "fragmentation_after": frag_at_barrier,
        "est_disruption_s": {t: round(sum(
            m["est_cost_s"] for m in moves
            if f"{m['namespace']}/{m['pod']}" == t), 3)
            for t in tenant_moves},
        "tenant_moves": tenant_moves,
    }
