"""tpumounter CLI: local single-node mode + master client.

Two families of verbs:

  Local (no Kubernetes anywhere — the SURVEY.md §7 "minimum end-to-end
  slice" / BASELINE config 1):
    devices                          chip inventory + busy holders
    probe                            native layer + libtpu status
    mount   --target-dev DIR [--pid N] [--cgroup DIR] --chips N | --uuid U..
    unmount --target-dev DIR [--pid N] [--cgroup DIR] --uuid U.. [--force]

  Remote (against a running master, same HTTP API as the reference's
  QuickStart curl examples). `--master` accepts a single URL or a
  comma-separated replica list: endpoints fail over on connection
  errors and shard 307 redirects are followed transparently
  (rpc/http_failover.py + master/shard.py):
    add     --master URL[,URL...] --namespace NS --pod POD --num N
    bulk-add --master URL --target [NS/]POD[:CHIPS] ...   one request,
                                   many mounts (POST /batch/addtpu)
    remove  --master URL --namespace NS --pod POD --uuids U,U [--force]
    migrate start|status|abort     live chip migration between pods
    audit   [--pod POD] [--trace ID] [--op PREFIX]   the audit trail
    trace ID                       assembled waterfall for one trace
                                   (master + federated worker spans)
    why ID                         the dominant critical-path phase of
                                   one trace and its share of wall time
                                   (exit 3 on incomplete assembly)
    timeline [--node N] [--trace ID] [--kind K] [--since F] [--until T]
                                   incident flight recorder: spans,
                                   audit, Events, ApiHealth, recovery
                                   markers merged chronologically
    fleet                          federated per-node fleet rollup
                                   (stale nodes flagged on stderr)
    slo                            SLO burn-rate evaluation with
                                   per-objective fast/slow burn windows
    tenants [--tenant T]           per-tenant disruption ledger: every
                                   window attributed to a cause + trace
    capacity [--accel-type T]      capacity & fragmentation pane: fleet
                                   chip inventory, ICI fragmentation
                                   index, per-size feasibility table,
                                   headroom forecast (exit 3 when an
                                   intent shape is infeasible)
    shards                         shard -> owner replica table
    recovery [--evacuate NODE]     node-failure recovery plane: liveness
                                   verdicts + evacuation history
    defrag [--plan|--run|--pause]  ICI defragmenter: plan/run/pause a
                                   capacity-recovery migration sequence
                                   (no flag: the state pane; exit 3
                                   when the controller is gated)
    autoscale [--pause|--resume|--evaluate]
                                   closed-loop autoscaler: per-tenant
                                   throughput fits + recent decisions
                                   (no flag: the state pane; exit 3
                                   when gated or paused)
    apihealth                      API-outage degraded mode: ApiHealth
                                   verdict, cache staleness, write-behind
                                   queue (exit 3 when not healthy)
                                   (the observability verbs accept
                                   --read-token: the read-only scope)

The reference has no CLI at all (interaction is raw curl,
docs/guide/QuickStart.md).

Exit codes (scriptable — a bad request is not a rollback):
    0  success
    1  generic error (transport failure, unexpected status)
    2  request rejected before anything moved (source == destination,
       unknown pod, already-migrating: any HTTP 4xx)
    3  migration failed mid-flight (rolled back / failed / aborted)
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request

from gpumounter_tpu.config import get_config
from gpumounter_tpu.device.backend import backend_from_config
from gpumounter_tpu.utils.log import init_logger


def _backend():
    return backend_from_config(get_config())


def cmd_devices(args) -> int:
    backend = _backend()
    devices = backend.list_devices()
    out = []
    for dev in devices:
        entry = {
            "index": dev.index, "uuid": dev.uuid, "path": dev.device_path,
            "major": dev.major, "minor": dev.minor,
        }
        if args.busy:
            entry["holder_pids"] = backend.running_pids(dev)
        out.append(entry)
    print(json.dumps(out, indent=2))
    return 0


def cmd_probe(args) -> int:
    from gpumounter_tpu import native

    lib = native.load_native()
    report = {
        "native_lib": "loaded" if lib is not None else "unavailable",
        "libtpu": native.libtpu_probe(),
        "chips": len(_backend().list_devices()),
    }
    from gpumounter_tpu.cgroup.naming import (
        detect_cgroup_driver,
        detect_cgroup_version,
    )
    cfg = get_config()
    report["cgroup_version"] = detect_cgroup_version(cfg.cgroup_root)
    report["cgroup_driver"] = detect_cgroup_driver(cfg.cgroup_root)
    print(json.dumps(report, indent=2))
    return 0


def _local_mounter_and_target(args):
    from gpumounter_tpu.worker.mounter import MountTarget, TpuMounter

    backend = _backend()
    mounter = TpuMounter(backend)
    target = MountTarget(
        dev_dir=args.target_dev,
        cgroup_dirs=[args.cgroup] if args.cgroup else [],
        ns_pid=args.pid,
        description=f"local:{args.target_dev}")
    return backend, mounter, target


def cmd_mount(args) -> int:
    backend, mounter, target = _local_mounter_and_target(args)
    devices = backend.list_devices()
    chosen = []
    if args.uuid:
        by_uuid = {d.uuid: d for d in devices}
        for u in args.uuid:
            if u not in by_uuid:
                print(f"error: no device with uuid {u}", file=sys.stderr)
                return 1
            chosen.append(by_uuid[u])
    else:
        chosen = devices[:args.chips]
        if len(chosen) < args.chips:
            print(f"error: only {len(chosen)} chip(s) available",
                  file=sys.stderr)
            return 1
    for dev in chosen:
        timings = mounter.mount(target, dev)
        print(json.dumps({"mounted": dev.uuid, "timings_ms": timings}))
    return 0


def cmd_unmount(args) -> int:
    from gpumounter_tpu.worker.mounter import TpuBusyError

    backend, mounter, target = _local_mounter_and_target(args)
    rc = 0
    for u in args.uuid:
        dev = backend.device_by_uuid(u)
        if dev is None:
            print(f"error: no device with uuid {u}", file=sys.stderr)
            rc = 1
            continue
        try:
            timings = mounter.unmount(target, dev, force=args.force)
            print(json.dumps({"unmounted": dev.uuid, "timings_ms": timings}))
        except TpuBusyError as exc:
            print(f"busy: {exc}", file=sys.stderr)
            rc = 2
    return rc


def _endpoints(args, token: str | None):
    """The failover client over `--master` (a URL or a comma-separated
    replica list): tries replicas in order, follows shard 307 redirects
    re-sending the body, and fails over on connection errors/503s
    (rpc/http_failover.py)."""
    from gpumounter_tpu.rpc.http_failover import MasterEndpoints
    return MasterEndpoints(args.master, token=token)


def _http(args, method: str, path: str, form: dict | None = None,
          token: str | None = None,
          json_body: dict | None = None) -> tuple[int, str]:
    """One master request; exits 1 with a one-line error when every
    replica is unreachable (a traceback is not a CLI answer)."""
    from gpumounter_tpu.rpc.http_failover import EndpointError
    try:
        return _endpoints(args, token).request(
            method, path, form=form, json_body=json_body)
    except EndpointError as exc:
        print(f"error: {exc}", file=sys.stderr)
        raise SystemExit(1)


def _remote_token(args) -> str | None:
    """--token wins (--token '' forces no credentials); else
    TPUMOUNTER_AUTH_TOKEN[_FILE] via the config. A broken token file
    is a one-line error, not a traceback."""
    explicit = getattr(args, "token", None)
    if explicit is not None:
        return explicit or None
    from gpumounter_tpu.config import get_config
    from gpumounter_tpu.utils.auth import AuthConfigError, resolve_token
    try:
        return resolve_token(get_config())
    except AuthConfigError as exc:
        print(f"auth: {exc} (pass --token, or --token '' to send none)",
              file=sys.stderr)
        raise SystemExit(2)


def cmd_add(args) -> int:
    path = (f"/addtpu/namespace/{args.namespace}"
            f"/pod/{args.pod}/tpu/{args.num}"
            f"/isEntireMount/{str(args.entire).lower()}")
    status, body = _http(args, "GET", path, token=_remote_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def cmd_remove(args) -> int:
    path = (f"/removetpu/namespace/{args.namespace}"
            f"/pod/{args.pod}/force/{str(args.force).lower()}")
    status, body = _http(args, "POST", path, form={"uuids": args.uuids},
                         token=_remote_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def _intent_path(args, with_pod: bool = True) -> str:
    if with_pod:
        return f"/intents/{args.namespace}/{args.pod}"
    return "/intents"


def cmd_intent_set(args) -> int:
    payload = {"desiredChips": args.chips, "minChips": args.min_chips,
               "priority": args.priority}
    status, body = _http(args, "PUT", _intent_path(args),
                         json_body=payload, token=_remote_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def cmd_intent_get(args) -> int:
    status, body = _http(args, "GET", _intent_path(args),
                         token=_remote_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def cmd_intent_delete(args) -> int:
    status, body = _http(args, "DELETE", _intent_path(args),
                         token=_remote_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def cmd_intent_list(args) -> int:
    status, body = _http(args, "GET", _intent_path(args, with_pod=False),
                         token=_remote_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def _obs_token(args) -> str | None:
    """--read-token (the read-only observability scope) wins over the
    mutate token resolution — scrape/debug boxes usually hold only it."""
    read = getattr(args, "read_token", None)
    if read:
        return read
    return _remote_token(args)


def cmd_audit(args) -> int:
    params = {k: v for k, v in (
        ("namespace", args.namespace), ("pod", args.pod), ("op", args.op),
        ("trace", args.trace), ("outcome", args.outcome),
        ("limit", str(args.limit))) if v}
    path = f"/audit?{urllib.parse.urlencode(params)}"
    status, body = _http(args, "GET", path, token=_obs_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def cmd_trace(args) -> int:
    status, body = _http(args, "GET", f"/trace/{args.id}",
                         token=_obs_token(args))
    print(body.rstrip())
    if status == 404:
        return 2  # unknown/expired trace id: rejected, not a failure
    return 0 if status == 200 else 1


def cmd_why(args) -> int:
    """Answer "why was this operation slow" for one trace id: fetch
    the assembled waterfall (GET /trace/<id> — master + federated
    worker spans joined by obs/assembly.py) and name the dominant
    critical-path phase and its share of wall time. Exit 2 when the
    trace is unknown/expired, 3 when the assembly is incomplete
    (orphan spans / a missing worker half — the verdict would lie)."""
    status, body = _http(args, "GET", f"/trace/{args.id}",
                         token=_obs_token(args))
    if status == 404:
        print(body.rstrip(), file=sys.stderr)
        return 2
    if status != 200:
        print(body.rstrip(), file=sys.stderr)
        return 1
    try:
        payload = json.loads(body)
    except ValueError:
        print("error: unparseable /trace payload", file=sys.stderr)
        return 1
    nodes = payload.get("nodes") or []
    print(f"trace {args.id}: {payload.get('op') or '?'} took "
          f"{payload.get('wall_ms', 0)}ms across "
          f"{len(payload.get('spans', []))} span(s)"
          + (f" on {', '.join(nodes)}" if nodes else ""))
    for entry in payload.get("critical_path", []):
        print(f"  {entry.get('phase', '?'):<20} "
              f"{entry.get('ms', 0.0):>10.3f} ms  "
              f"{entry.get('share', 0.0) * 100:5.1f}%")
    dominant = payload.get("dominant") or {}
    if dominant:
        print(f"dominant phase: {dominant.get('phase')} "
              f"({dominant.get('share', 0.0):.0%} of wall time)")
    if dominant and nodes:
        # Gray-failure verdict: if a node this trace touched is
        # quarantined, the slow phase is the limping node, not the
        # control plane — name it before the operator starts digging
        # through warm-pool stats. Best-effort: a master without the
        # health plane (or an auth scope without it) just skips this.
        try:
            h_status, h_body = _http(args, "GET", "/health/nodes",
                                     token=_obs_token(args))
            health_nodes = (json.loads(h_body).get("nodes") or {}
                            if h_status == 200 else {})
        except (SystemExit, ValueError):
            health_nodes = {}
        if not isinstance(health_nodes, dict):
            health_nodes = {}  # not a health payload: skip the verdict
        for node in nodes:
            pane = health_nodes.get(node) or {}
            if pane.get("state") == "quarantined":
                print(f"verdict: quarantine — node {node} is quarantined "
                      f"({pane.get('reason') or 'no reason recorded'}); "
                      f"this operation ran through a limping node")
                break
    if dominant.get("phase") == "slave_pod_schedule":
        # Name the COLD-MOUNT CAUSE: the slave_pod_schedule spans carry
        # the allocator's warm-pool outcome (pool_hit/pool_gap), so a
        # dominant scheduling phase is attributable to warm-pool
        # starvation vs plain scheduler wait instead of a shrug.
        hits = gap = 0
        enabled = False
        seen = False
        for entry in payload.get("spans", []):
            if entry.get("name") != "mount.slave_pod_schedule":
                continue
            attrs = entry.get("attrs") or {}
            if "pool_gap" not in attrs and "pool_hit" not in attrs:
                continue
            seen = True
            hits += int(attrs.get("pool_hit", 0) or 0)
            gap += int(attrs.get("pool_gap", 0) or 0)
            enabled = enabled or bool(attrs.get("pool_enabled"))
        if not seen:
            print("cold-mount cause: unknown (no warm-pool outcome on "
                  "the scheduling span — pre-capacity worker?)")
        elif not enabled:
            print(f"cold-mount cause: scheduler wait ({gap} chip(s) "
                  f"cold-created; warm pool disabled on this node)")
        elif gap > 0:
            print(f"cold-mount cause: warm-pool starvation ({gap} "
                  f"chip(s) fell to the cold path, {hits} adopted "
                  f"warm — the pool ran dry)")
        else:
            print(f"cold-mount cause: scheduler wait ({hits} chip(s) "
                  f"adopted warm yet scheduling still dominated)")
    if not payload.get("complete", False):
        orphans = payload.get("orphans") or []
        missing = payload.get("missing_worker_halves") or []
        print(f"INCOMPLETE assembly: {len(orphans)} orphan span(s), "
              f"{len(missing)} rpc span(s) missing their worker half — "
              f"the breakdown above understates remote phases",
              file=sys.stderr)
        return 3
    return 0


def cmd_timeline(args) -> int:
    """The incident flight recorder's merged chronological timeline
    (GET /timeline): root/error spans, audit records, k8s Events,
    ApiHealth transitions and recovery markers, oldest first. JSON on
    stdout; a one-line-per-record rendering on stderr for humans."""
    params = {k: v for k, v in (
        ("node", args.node), ("trace", args.trace), ("kind", args.kind),
        ("from", args.since), ("to", args.until),
        ("limit", str(args.limit))) if v}
    path = "/timeline" + (f"?{urllib.parse.urlencode(params)}"
                          if params else "")
    status, body = _http(args, "GET", path, token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        records = json.loads(body).get("records", [])
    except ValueError:
        return 1
    for rec in records:
        trace_id = rec.get("trace_id") or "-"
        node = rec.get("node") or "-"
        print(f"{rec.get('at', 0):.3f} [{rec.get('kind', '?'):>9}] "
              f"{node:<12} {rec.get('summary', '')} (trace {trace_id})",
              file=sys.stderr)
    return 0


def cmd_fleet(args) -> int:
    status, body = _http(args, "GET", "/fleet", token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        nodes = json.loads(body).get("nodes", {})
    except ValueError:
        return 1
    # Flag stale entries loudly (stderr keeps stdout parseable JSON):
    # a stale node's numbers describe the LAST successful collect, and
    # before stale_age_s they were indistinguishable from fresh ones.
    for name in sorted(nodes):
        entry = nodes[name]
        if entry.get("stale"):
            age = entry.get("stale_age_s")
            when = (f"last collected {age}s ago" if age is not None
                    else "NEVER collected successfully")
            print(f"STALE: node {name} {when} "
                  f"({entry.get('error', 'unreachable')})",
                  file=sys.stderr)
    return 0


def cmd_tenants(args) -> int:
    """The per-tenant disruption ledger (GET /tenants): every window a
    tenant's training loop felt, attributed to its cause and joined to
    its control-plane trace. Exit 3 when any disruption window is still
    open — scriptable like `tpumounter slo`."""
    status, body = _http(args, "GET", "/tenants", token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        payload = json.loads(body)
    except ValueError:
        return 1
    tenants = payload.get("tenants", {})
    if args.tenant:
        tenants = {k: v for k, v in tenants.items() if k == args.tenant}
        if not tenants:
            print(f"error: no tenant {args.tenant!r} in the ledger",
                  file=sys.stderr)
            return 2
    open_windows = 0
    for name in sorted(tenants):
        entry = tenants[name]
        dis = entry.get("disruption", {})
        open_windows += len(dis.get("open", []))
        causes = ", ".join(
            f"{cause}: {agg.get('windows', 0)}x p95 "
            f"{agg.get('p95_ms', 0)}ms"
            for cause, agg in sorted(dis.get("by_cause", {}).items()))
        print(f"tenant {name}: steps={entry.get('steps', 0)} "
              f"tokens/s={entry.get('tokens_per_s', 0)} "
              f"disrupted {dis.get('total_seconds', 0)}s over "
              f"{dis.get('total_windows', 0)} window(s)"
              + (f" [{causes}]" if causes else ""), file=sys.stderr)
        for w in dis.get("open", []):
            print(f"  OPEN: {w.get('cause')} for {w.get('age_s')}s "
                  f"(trace {w.get('trace_id') or '-'})", file=sys.stderr)
    return 3 if open_windows else 0


def cmd_capacity(args) -> int:
    """The capacity & fragmentation pane (GET /capacity): fleet chip
    inventory, per-host and fleet ICI fragmentation indices, the
    per-size allocation-feasibility table and the headroom forecast.
    JSON on stdout; one-line verdicts on stderr. Exit 2 when
    --accel-type names an unknown shape; exit 3 when that shape is
    infeasible right now, or (without --accel-type) when the declared
    intent demand no longer fits free capacity."""
    path = "/capacity"
    if args.accel_type:
        path += f"?accel_type={urllib.parse.quote(args.accel_type)}"
    status, body = _http(args, "GET", path, token=_obs_token(args))
    print(body.rstrip())
    if status == 404 and args.accel_type:
        return 2
    if status != 200:
        return 1
    try:
        payload = json.loads(body)
    except ValueError:
        return 1
    fleet = payload.get("fleet", {})
    print(f"fleet: {fleet.get('free', 0)}/{fleet.get('total', 0)} "
          f"chip(s) free (warm {fleet.get('warm', 0)}, fenced "
          f"{fleet.get('fenced', 0)}), fragmentation index "
          f"{fleet.get('fragmentation_index', 0.0)}, largest block "
          f"{fleet.get('largest_block', 0)} across "
          f"{fleet.get('hosts_reporting', 0)}/{fleet.get('hosts', 0)} "
          f"reporting host(s)", file=sys.stderr)
    infeasible_requested = False
    for accel, entry in sorted((payload.get("feasibility") or {}).items()):
        verdict = entry.get("verdict", "?")
        line = (f"{accel}: {verdict} "
                f"({entry.get('hosts_admissible_now', 0)}/"
                f"{entry.get('hosts_needed', 0)} host(s) admissible "
                f"now, {entry.get('hosts_after_defrag', 0)} after "
                f"defrag)")
        blocking = entry.get("blocking_hosts") or []
        if blocking:
            line += f" blocking: {', '.join(blocking)}"
        print(line, file=sys.stderr)
        if args.accel_type and verdict == "infeasible":
            infeasible_requested = True
    headroom = payload.get("headroom", {})
    print(f"headroom: {headroom.get('forecast', '?')} "
          f"(free {headroom.get('free_chips', 0)}, queue depth "
          f"{headroom.get('queue_depth', 0)}, "
          f"{headroom.get('tokens_per_s', 0)} tokens/s across "
          f"{headroom.get('tenants', 0)} tenant(s))", file=sys.stderr)
    demand = payload.get("demand", {})
    if demand.get("intents") and not demand.get("satisfiable", True):
        print(f"DEMAND UNSATISFIABLE: declared intents want "
              f"{demand.get('gap', 0)} more chip(s) than free+warm "
              f"capacity holds", file=sys.stderr)
        if not args.accel_type:
            return 3
    return 3 if infeasible_requested else 0


def cmd_apihealth(args) -> int:
    """The master's API-outage degraded-mode pane (GET /apihealth):
    the ApiHealth verdict (healthy/degraded/down), the store cache's
    staleness stamps, and the write-behind queue books. Exit 3 when
    the API is degraded/down or deferred writes are still pending —
    scriptable like `tpumounter slo`."""
    status, body = _http(args, "GET", "/apihealth",
                         token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        payload = json.loads(body)
    except ValueError:
        return 1
    api = payload.get("api", {})
    state = api.get("state", "unknown")
    pending = payload.get("store", {}).get("writeBehind", {}) \
        .get("pending", 0)
    print(f"api: {state} (for {api.get('sinceS', 0)}s, "
          f"{api.get('consecutiveFailures', 0)} consecutive failure(s))"
          + (f"; last error: {api.get('lastError')}"
             if api.get("lastError") and state != "healthy" else ""),
          file=sys.stderr)
    if pending:
        print(f"write-behind: {pending} deferred write(s) pending "
              f"replay", file=sys.stderr)
    return 3 if state != "healthy" or pending else 0


def cmd_shards(args) -> int:
    """The shard table: which master replica owns which shard."""
    status, body = _http(args, "GET", "/shards", token=_obs_token(args))
    print(body.rstrip())
    return 0 if status == 200 else 1


def cmd_recovery(args) -> int:
    """The recovery plane: per-node liveness verdicts + evacuation
    history (GET /recovery), or --evacuate NODE to trigger a manual
    evacuation (POST; requires the mutate token). Exit 3 when any node
    is suspect/evacuated — scriptable like `tpumounter slo`."""
    if args.evacuate:
        status, body = _http(args, "POST",
                             f"/recovery/evacuate/{args.evacuate}",
                             token=_remote_token(args))
        print(body.rstrip())
        return 0 if status == 200 else 1
    status, body = _http(args, "GET", "/recovery", token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        nodes = json.loads(body).get("nodes", {})
    except ValueError:
        return 1
    unhealthy = any(entry.get("status") in ("suspect", "evacuated")
                    for entry in nodes.values())
    return 3 if unhealthy else 0


def cmd_health(args) -> int:
    """The gray-failure health plane: per-node scorer verdicts +
    quarantine states (GET /health/nodes), or --quarantine NODE /
    --release NODE to drive the state machine by hand (POST; mutate
    token). A 409 refusal (release of a non-quarantined node, quarantine
    of an evacuated one) exits 2: the plane refused, nothing changed.
    Exit 3 while ANY node is quarantined — scriptable like
    `tpumounter recovery`."""
    if args.quarantine or args.release:
        node = args.quarantine or args.release
        action = "quarantine" if args.quarantine else "release"
        body_json: dict = {"action": action}
        if args.quarantine and args.reason:
            body_json["reason"] = args.reason
        status, body = _http(args, "POST", f"/health/quarantine/{node}",
                             json_body=body_json,
                             token=_remote_token(args))
        print(body.rstrip())
        if status == 409:
            return 2
        return 0 if status == 200 else 1
    status, body = _http(args, "GET", "/health/nodes",
                         token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        payload = json.loads(body)
    except ValueError:
        return 1
    nodes = payload.get("nodes") or {}
    quarantined = any(entry.get("state") == "quarantined"
                      and not entry.get("evacuated")
                      for entry in nodes.values())
    return 3 if quarantined else 0


def cmd_defrag(args) -> int:
    """The ICI defragmenter. No flag: the state pane (GET /defrag, exit
    3 when the controller is gated — API degraded or an SLO burning).
    --plan computes and adopts a migration plan, --run executes the
    adopted plan, --pause stops after the in-flight move (all POST;
    mutate token). A 409/503 refusal (stale snapshot, SLO burn,
    degraded API) exits 2: the controller refused, nothing moved."""
    if args.plan:
        body_json = ({"target_block": args.target_block}
                     if args.target_block else {})
        status, body = _http(args, "POST", "/defrag/plan",
                             json_body=body_json,
                             token=_remote_token(args))
    elif args.run:
        body_json = {"plan_id": args.plan_id} if args.plan_id else {}
        status, body = _http(args, "POST", "/defrag/run",
                             json_body=body_json,
                             token=_remote_token(args))
    elif args.pause:
        status, body = _http(args, "POST", "/defrag/pause",
                             json_body={}, token=_remote_token(args))
    else:
        status, body = _http(args, "GET", "/defrag",
                             token=_obs_token(args))
        print(body.rstrip())
        if status != 200:
            return 1
        try:
            gates = json.loads(body).get("gates", {})
        except ValueError:
            return 1
        gated = (not gates.get("api_ok", True)
                 or gates.get("slo_burning"))
        return 3 if gated else 0
    print(body.rstrip())
    if status in (409, 503):
        return 2
    return 0 if status == 200 else 1


def cmd_autoscale(args) -> int:
    """The closed-loop autoscaler. No flag: the state pane (GET
    /autoscale — per-tenant throughput fits, gates, recent decisions;
    exit 3 when the controller is gated or paused). --pause parks it,
    --resume un-parks it, --evaluate forces one decision pass now (all
    POST; mutate token). A 409/503 refusal (SLO burn, degraded API,
    stale telemetry) exits 2: the controller refused, nothing scaled."""
    if args.pause:
        status, body = _http(args, "POST", "/autoscale/pause",
                             json_body={}, token=_remote_token(args))
    elif args.resume:
        status, body = _http(args, "POST", "/autoscale/resume",
                             json_body={}, token=_remote_token(args))
    elif args.evaluate:
        status, body = _http(args, "POST", "/autoscale/evaluate",
                             json_body={}, token=_remote_token(args))
    else:
        status, body = _http(args, "GET", "/autoscale",
                             token=_obs_token(args))
        print(body.rstrip())
        if status != 200:
            return 1
        try:
            pane = json.loads(body)
        except ValueError:
            return 1
        gates = pane.get("gates", {})
        gated = (not gates.get("api_ok", True)
                 or gates.get("slo_burning")
                 or pane.get("paused"))
        return 3 if gated else 0
    print(body.rstrip())
    if status in (409, 503):
        return 2
    return 0 if status == 200 else 1


def cmd_shares(args) -> int:
    """Fractional chip shares. No flag: the share books (GET /shares;
    exit 3 when any chip's booked load exceeds the weight capacity —
    a books bug worth a page). --admit books shares for a tenant
    (--pod, --profile, --chips, --weight, --rate-budget, candidate
    chips via repeated --chip UUID=NODE); --release drops every share
    the tenant holds. A 409 admission refusal exits 2: the packer
    refused, nothing was booked."""
    if args.admit or args.release:
        if not args.pod:
            print("error: --pod is required with --admit/--release",
                  file=sys.stderr)
            return 2
    if args.admit:
        inventory = {}
        for raw in args.chip:
            uuid, sep, node = raw.partition("=")
            if not sep or not uuid or not node:
                print(f"error: bad --chip {raw!r} (want UUID=NODE)",
                      file=sys.stderr)
                return 2
            inventory[uuid] = node
        status, body = _http(
            args, "POST", "/shares",
            json_body={"namespace": args.namespace, "pod": args.pod,
                       "profile": args.profile, "chips": args.chips,
                       "weight": args.weight,
                       "rate_budget": args.rate_budget,
                       "inventory": inventory},
            token=_remote_token(args))
        print(body.rstrip())
        if status == 409:
            return 2
        return 0 if status == 200 else 1
    if args.release:
        status, body = _http(
            args, "DELETE",
            f"/shares/{urllib.parse.quote(args.namespace)}/"
            f"{urllib.parse.quote(args.pod)}",
            token=_remote_token(args))
        print(body.rstrip())
        return 0 if status == 200 else 1
    status, body = _http(args, "GET", "/shares", token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        payload = json.loads(body)
    except ValueError:
        return 1
    capacity = payload.get("weight_capacity", 0)
    overbooked = False
    for uuid, entry in sorted((payload.get("chips") or {}).items()):
        line = (f"{uuid} on {entry.get('node', '?')}: "
                f"{entry.get('tenants', 0)} tenant(s), load "
                f"{entry.get('load', 0)}/{capacity} "
                f"[{', '.join(entry.get('profiles') or [])}]")
        if capacity and entry.get("load", 0) > capacity:
            line += " OVERBOOKED"
            overbooked = True
        print(line, file=sys.stderr)
    totals = payload.get("totals", {})
    print(f"{totals.get('shares', 0)} share(s) over "
          f"{totals.get('chips', 0)} chip(s), "
          f"{totals.get('shared_chips', 0)} co-located", file=sys.stderr)
    return 3 if overbooked else 0


def _parse_bulk_target(raw: str, default_ns: str) -> dict:
    """"[ns/]pod[:chips]" -> a /batch/addtpu target entry."""
    body, _, chips = raw.partition(":")
    ns, _, pod = body.rpartition("/")
    entry = {"namespace": ns or default_ns, "pod": pod or body}
    if chips:
        try:
            entry["chips"] = int(chips)
        except ValueError:
            raise SystemExit(f"error: bad --target {raw!r} "
                             f"(chips must be an integer)")
    return entry


def cmd_bulk_add(args) -> int:
    """One request, many mounts: exit 0 only when EVERY target mounted
    (per-target results are printed either way)."""
    targets = [_parse_bulk_target(t, args.namespace)
               for t in args.target]
    if args.entire:
        for t in targets:
            t["isEntireMount"] = True
    status, body = _http(args, "POST", "/batch/addtpu",
                         json_body={"targets": targets},
                         token=_remote_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        summary = json.loads(body).get("summary", {})
    except ValueError:
        return 1
    return 0 if summary.get("success") == summary.get("total") else 1


def cmd_slo(args) -> int:
    """Print the SLO evaluation; exit 3 when any objective is in breach
    (scriptable: a deploy gate can `tpumounter slo && roll`). Besides
    the raw JSON, each objective gets a one-line verdict naming its
    fast/slow burn against their windows and the breach threshold —
    so WHICH window tripped is visible without reading the payload."""
    status, body = _http(args, "GET", "/slo", token=_obs_token(args))
    print(body.rstrip())
    if status != 200:
        return 1
    try:
        payload = json.loads(body)
    except ValueError:
        return 1
    windows = payload.get("windows_s", {})
    fast_s, slow_s = windows.get("fast", 0), windows.get("slow", 0)
    threshold = payload.get("burn_threshold", 0)
    breached = False
    for obj in payload.get("objectives", []):
        burn_fast = obj.get("burn_fast", 0.0)
        burn_slow = obj.get("burn_slow", 0.0)
        if obj.get("breached"):
            breached = True
            verdict = "BREACH (both windows over threshold)"
        elif burn_fast >= threshold > burn_slow:
            verdict = "ok (fast window hot, slow window holding)"
        elif burn_slow >= threshold > burn_fast:
            verdict = "ok (slow window elevated, fast window calm)"
        else:
            verdict = "ok"
        print(f"{obj.get('name')}: burn {burn_fast:.2f}x/{fast_s:.0f}s "
              f"(fast) {burn_slow:.2f}x/{slow_s:.0f}s (slow), "
              f"threshold {threshold:.1f}x -> {verdict}",
              file=sys.stderr)
    return 3 if breached else 0


EXIT_OK = 0
EXIT_ERROR = 1
EXIT_REJECTED = 2    # 4xx: bad request, nothing moved
EXIT_FAILED = 3      # migration went terminal without succeeding


def _terminal_exit(journal: dict) -> int:
    return EXIT_OK if journal.get("outcome") == "succeeded" else EXIT_FAILED


def cmd_migrate_start(args) -> int:
    import time

    payload = {
        "source": {"namespace": args.namespace, "pod": args.pod},
        "destination": {"namespace": args.dest_namespace or args.namespace,
                        "pod": args.dest_pod},
        "checkpoint": bool(args.checkpoint),
    }
    token = _remote_token(args)
    status, body = _http(args, "POST", "/migrate",
                         json_body=payload, token=token)
    print(body.rstrip())
    if 400 <= status < 500:
        return EXIT_REJECTED
    if status != 200:
        return EXIT_ERROR
    if not args.wait:
        return EXIT_OK
    mid = json.loads(body)["id"]
    endpoints = _endpoints(args, token)
    deadline = time.monotonic() + args.wait_timeout
    while time.monotonic() < deadline:
        # Transient poll failures (every replica restarting, blip) must
        # not abort the wait: the journal survives in pod annotations
        # and a restarted/peer master re-adopts the migration, so keep
        # polling until the deadline.
        try:
            status, body = endpoints.request("GET", f"/migrations/{mid}")
        except (urllib.error.URLError, OSError):
            status = None
        if status == 200:
            journal = json.loads(body)
            if journal.get("outcome"):
                print(json.dumps(journal, indent=1))
                return _terminal_exit(journal)
        time.sleep(args.poll_interval)
    print(f"error: migration {mid} not terminal within "
          f"{args.wait_timeout}s", file=sys.stderr)
    return EXIT_ERROR


def _print_phase_durations(journal: dict) -> None:
    """One stderr line per terminal migration naming where the wall
    time went — the journal's per-phase durations are the same numbers
    the defrag cost model reads, so an operator sees exactly what a
    future move of this tenant is priced at."""
    durations = journal.get("phase_durations_s")
    if not durations or not journal.get("outcome"):
        return
    rendered = " ".join(f"{phase}={seconds:.2f}s"
                        for phase, seconds in durations.items())
    total = sum(durations.values())
    print(f"{journal.get('id')}: {journal.get('outcome')} in "
          f"{total:.2f}s ({rendered})", file=sys.stderr)


def cmd_migrate_status(args) -> int:
    path = f"/migrations/{args.id}" if args.id else "/migrations"
    status, body = _http(args, "GET", path, token=_remote_token(args))
    print(body.rstrip())
    if 400 <= status < 500:
        return EXIT_REJECTED
    if status != 200:
        return EXIT_ERROR
    try:
        payload = json.loads(body)
    except ValueError:
        return EXIT_OK
    for journal in (payload.get("migrations", [])
                    if args.id is None else [payload]):
        _print_phase_durations(journal)
    return EXIT_OK


def cmd_migrate_abort(args) -> int:
    status, body = _http(args, "POST", f"/migrations/{args.id}/abort",
                         token=_remote_token(args))
    print(body.rstrip())
    if 400 <= status < 500:
        return EXIT_REJECTED
    return EXIT_OK if status == 200 else EXIT_ERROR


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpumounter")
    sub = p.add_subparsers(dest="verb", required=True)

    d = sub.add_parser("devices", help="list chip inventory")
    d.add_argument("--busy", action="store_true",
                   help="include holder PIDs per chip")
    d.set_defaults(fn=cmd_devices)

    pr = sub.add_parser("probe", help="native layer / libtpu / cgroup status")
    pr.set_defaults(fn=cmd_probe)

    def _local_args(sp):
        sp.add_argument("--target-dev", required=True,
                        help="device dir of the target (its /dev)")
        sp.add_argument("--pid", type=int, default=None,
                        help="PID whose mount namespace to enter")
        sp.add_argument("--cgroup", default="",
                        help="target cgroup dir for device permission")

    m = sub.add_parser("mount", help="local mount (no k8s)")
    _local_args(m)
    m.add_argument("--chips", type=int, default=1)
    m.add_argument("--uuid", action="append", default=[])
    m.set_defaults(fn=cmd_mount)

    um = sub.add_parser("unmount", help="local unmount (no k8s)")
    _local_args(um)
    um.add_argument("--uuid", action="append", required=True)
    um.add_argument("--force", action="store_true")
    um.set_defaults(fn=cmd_unmount)

    a = sub.add_parser("add", help="hot-add via a running master")
    a.add_argument("--master", required=True,
                   help="master URL, or a comma-separated replica list "
                        "(failover + shard-redirect following)")
    a.add_argument("--namespace", default="default")
    a.add_argument("--pod", required=True)
    a.add_argument("--num", type=int, default=1)
    a.add_argument("--entire", action="store_true")
    a.add_argument("--token", default=None,
                   help="master bearer token (default: "
                        "TPUMOUNTER_AUTH_TOKEN[_FILE])")
    a.set_defaults(fn=cmd_add)

    # Bulk mount: one POST /batch/addtpu covering many pods; the master
    # groups targets by owning shard and node (docs/FAQ.md on when bulk
    # beats per-pod adds).
    ba = sub.add_parser("bulk-add", help="mount chips into MANY pods in "
                                         "one request")
    ba.add_argument("--master", required=True,
                    help="master URL, or a comma-separated replica list")
    ba.add_argument("--namespace", default="default",
                    help="default namespace for --target entries")
    ba.add_argument("--target", action="append", required=True,
                    metavar="[NS/]POD[:CHIPS]",
                    help="repeatable; e.g. --target serve-a:2 "
                         "--target jobs/serve-b")
    ba.add_argument("--entire", action="store_true",
                    help="entire-mount each target's chips")
    ba.add_argument("--token", default=None,
                    help="master bearer token (default: "
                         "TPUMOUNTER_AUTH_TOKEN[_FILE])")
    ba.set_defaults(fn=cmd_bulk_add)

    # Elastic intents: declare desired chip counts; the master's
    # reconciler converges and keeps converging (self-healing).
    it = sub.add_parser("intent", help="declarative chip-count intents")
    it_sub = it.add_subparsers(dest="intent_verb", required=True)

    def _intent_common(sp, with_pod=True):
        sp.add_argument("--master", required=True)
        if with_pod:
            sp.add_argument("--namespace", default="default")
            sp.add_argument("--pod", required=True)
        sp.add_argument("--token", default=None,
                        help="master bearer token (default: "
                             "TPUMOUNTER_AUTH_TOKEN[_FILE])")

    iset = it_sub.add_parser("set", help="declare desired chips for a pod")
    _intent_common(iset)
    iset.add_argument("--chips", type=int, required=True,
                      help="desired chip count the reconciler converges to")
    iset.add_argument("--min-chips", type=int, default=0,
                      help="acceptable floor under capacity pressure")
    iset.add_argument("--priority", type=int, default=0,
                      help="higher reconciles first when contended")
    iset.set_defaults(fn=cmd_intent_set)

    iget = it_sub.add_parser("get", help="show one pod's intent + status")
    _intent_common(iget)
    iget.set_defaults(fn=cmd_intent_get)

    idel = it_sub.add_parser("delete",
                             help="stop managing a pod (keeps its chips)")
    _intent_common(idel)
    idel.set_defaults(fn=cmd_intent_delete)

    ilist = it_sub.add_parser("list", help="all declared intents")
    _intent_common(ilist, with_pod=False)
    ilist.set_defaults(fn=cmd_intent_list)

    # Live migration: drain, snapshot, and re-mount a tenant's chip set
    # on another pod without restarting the tenant.
    mg = sub.add_parser("migrate", help="live chip migration between pods")
    mg_sub = mg.add_subparsers(dest="migrate_verb", required=True)

    def _migrate_common(sp):
        sp.add_argument("--master", required=True)
        sp.add_argument("--token", default=None,
                        help="master bearer token (default: "
                             "TPUMOUNTER_AUTH_TOKEN[_FILE])")

    ms = mg_sub.add_parser("start", help="migrate a pod's chips to "
                                         "another pod")
    _migrate_common(ms)
    ms.add_argument("--namespace", default="default")
    ms.add_argument("--pod", required=True, help="source pod")
    ms.add_argument("--dest-namespace", default=None,
                    help="destination namespace (default: --namespace)")
    ms.add_argument("--dest-pod", required=True, help="destination pod")
    ms.add_argument("--checkpoint", action="store_true",
                    help="checkpoint-assisted drain (migration v2): "
                         "snapshot tenant state before the chips move "
                         "so the drain window shrinks to a copy")
    ms.add_argument("--wait", action="store_true",
                    help="block until the migration is terminal")
    ms.add_argument("--wait-timeout", type=float, default=300.0)
    ms.add_argument("--poll-interval", type=float, default=0.5)
    ms.set_defaults(fn=cmd_migrate_start)

    mst = mg_sub.add_parser("status", help="one migration (--id) or all")
    _migrate_common(mst)
    mst.add_argument("--id", default=None)
    mst.set_defaults(fn=cmd_migrate_status)

    mab = mg_sub.add_parser("abort", help="abort an in-flight migration "
                                          "(rolls back to the source)")
    _migrate_common(mab)
    mab.add_argument("--id", required=True)
    mab.set_defaults(fn=cmd_migrate_abort)

    # Observability reads: what happened to a pod's chips, when, and
    # why was it slow (docs/RUNBOOK.md "Debugging a slow mount").
    def _obs_common(sp):
        sp.add_argument("--master", required=True)
        sp.add_argument("--token", default=None,
                        help="master bearer token (default: "
                             "TPUMOUNTER_AUTH_TOKEN[_FILE])")
        sp.add_argument("--read-token", default=None,
                        help="read-only observability token "
                             "(TPUMOUNTER_AUTH_READ_TOKEN scope)")

    au = sub.add_parser("audit", help="query the mutating-operation "
                                      "audit trail")
    _obs_common(au)
    au.add_argument("--namespace", default=None)
    au.add_argument("--pod", default=None)
    au.add_argument("--op", default=None,
                    help="operation prefix (http., worker., migrate...)")
    au.add_argument("--trace", default=None, help="exact trace id")
    au.add_argument("--outcome", default=None,
                    help="outcome prefix (Success, error, http 4...)")
    au.add_argument("--limit", type=int, default=100)
    au.set_defaults(fn=cmd_audit)

    tr = sub.add_parser("trace", help="dump all buffered spans for one "
                                      "trace id")
    _obs_common(tr)
    tr.add_argument("id", help="trace id (X-Tpumounter-Trace response "
                               "header / audit record trace_id)")
    tr.set_defaults(fn=cmd_trace)

    wy = sub.add_parser("why", help="name the dominant critical-path "
                                    "phase of one trace (exit 3 when "
                                    "the assembly is incomplete)")
    _obs_common(wy)
    wy.add_argument("id", help="trace id (X-Tpumounter-Trace response "
                               "header / audit record trace_id)")
    wy.set_defaults(fn=cmd_why)

    tl = sub.add_parser("timeline", help="incident flight recorder: the "
                                         "merged chronological timeline "
                                         "(spans, audit, Events, "
                                         "ApiHealth, recovery markers)")
    _obs_common(tl)
    tl.add_argument("--node", default=None, help="only this node")
    tl.add_argument("--trace", default=None, help="only this trace id")
    tl.add_argument("--kind", default=None,
                    help="span / audit / event / apihealth / recovery")
    tl.add_argument("--since", dest="since", default=None, metavar="FROM",
                    help="unix-seconds lower bound (?from=)")
    tl.add_argument("--until", dest="until", default=None, metavar="TO",
                    help="unix-seconds upper bound (?to=)")
    tl.add_argument("--limit", type=int, default=500)
    tl.set_defaults(fn=cmd_timeline)

    fl = sub.add_parser("fleet", help="federated fleet rollup: per-node "
                                      "mount p50/p95, warm-pool hit "
                                      "rate, breaker state")
    _obs_common(fl)
    fl.set_defaults(fn=cmd_fleet)

    sl = sub.add_parser("slo", help="SLO burn-rate evaluation (exit 3 "
                                    "when any objective is in breach)")
    _obs_common(sl)
    sl.set_defaults(fn=cmd_slo)

    tn = sub.add_parser("tenants", help="per-tenant disruption ledger: "
                                        "step rates, downtime windows "
                                        "attributed to their cause + "
                                        "trace (exit 3 when any window "
                                        "is still open)")
    _obs_common(tn)
    tn.add_argument("--tenant", default=None,
                    help="show only this tenant (exit 2 when absent)")
    tn.set_defaults(fn=cmd_tenants)

    sh = sub.add_parser("shards", help="shard table: which master "
                                       "replica owns which node shard")
    _obs_common(sh)
    sh.set_defaults(fn=cmd_shards)

    cp = sub.add_parser("capacity",
                        help="capacity & fragmentation pane: fleet "
                             "chip inventory, ICI fragmentation index, "
                             "per-size feasibility + headroom forecast "
                             "(exit 3 when --accel-type is infeasible "
                             "or declared demand no longer fits)")
    _obs_common(cp)
    cp.add_argument("--accel-type", default=None,
                    help="only this accelerator type's feasibility "
                         "(e.g. v5litepod-16; exit 2 when unknown, "
                         "3 when infeasible)")
    cp.set_defaults(fn=cmd_capacity)

    ah = sub.add_parser("apihealth",
                        help="API-outage degraded mode: ApiHealth "
                             "verdict + cache staleness + write-behind "
                             "queue (exit 3 when not healthy or writes "
                             "are pending)")
    _obs_common(ah)
    ah.set_defaults(fn=cmd_apihealth)

    rc = sub.add_parser("recovery", help="node-failure recovery plane: "
                                         "liveness verdicts + evacuation "
                                         "history (exit 3 when any node "
                                         "is suspect/evacuated)")
    _obs_common(rc)
    rc.add_argument("--evacuate", metavar="NODE", default=None,
                    help="manually evacuate NODE (operator-confirmed "
                         "death; needs the mutate token)")
    rc.set_defaults(fn=cmd_recovery)

    hl = sub.add_parser("health",
                        help="gray-failure health plane: per-node "
                             "scorer verdicts + quarantine states "
                             "(no flag: pane, exit 3 while any node is "
                             "quarantined; --quarantine/--release "
                             "mutate, exit 2 on a plane refusal)")
    _obs_common(hl)
    hl_group = hl.add_mutually_exclusive_group()
    hl_group.add_argument("--quarantine", metavar="NODE", default=None,
                          help="manually quarantine NODE (budget-exempt; "
                               "needs the mutate token)")
    hl_group.add_argument("--release", metavar="NODE", default=None,
                          help="release NODE straight to healthy "
                               "(needs the mutate token)")
    hl.add_argument("--reason", default=None,
                    help="with --quarantine: why (lands in the pane and "
                         "the flight recorder)")
    hl.set_defaults(fn=cmd_health)

    df = sub.add_parser("defrag",
                        help="ICI defragmenter: recover large-slice "
                             "capacity by live-migrating tenants off "
                             "fragmented hosts (no flag: state pane, "
                             "exit 3 when gated; --plan/--run/--pause "
                             "mutate, exit 2 on a controller refusal)")
    _obs_common(df)
    group = df.add_mutually_exclusive_group()
    group.add_argument("--plan", action="store_true",
                       help="compute + adopt a plan from a fresh "
                            "capacity snapshot")
    group.add_argument("--run", action="store_true",
                       help="execute the adopted plan")
    group.add_argument("--pause", action="store_true",
                       help="stop after the in-flight move")
    df.add_argument("--target-block", type=int, default=None,
                    help="ICI block size to recover (default: "
                         "DEFRAG_TARGET_BLOCK)")
    df.add_argument("--plan-id", default=None,
                    help="with --run: refuse unless this exact plan "
                         "is still adopted")
    df.set_defaults(fn=cmd_defrag)

    asc = sub.add_parser("autoscale",
                         help="closed-loop autoscaler: per-tenant "
                              "throughput fits + gated grow/shrink "
                              "decisions on elastic intents (no flag: "
                              "state pane, exit 3 when gated or "
                              "paused; --pause/--resume/--evaluate "
                              "mutate, exit 2 on a controller refusal)")
    _obs_common(asc)
    asc_group = asc.add_mutually_exclusive_group()
    asc_group.add_argument("--pause", action="store_true",
                           help="park the decision loop (passes still "
                                "observe; nothing actuates)")
    asc_group.add_argument("--resume", action="store_true",
                           help="un-park the decision loop")
    asc_group.add_argument("--evaluate", action="store_true",
                           help="force one decision pass now")
    asc.set_defaults(fn=cmd_autoscale)

    vs = sub.add_parser("shares",
                        help="fractional chip shares: the co-location "
                             "books (no flag: state pane, exit 3 when "
                             "any chip is over its weight capacity; "
                             "--admit/--release mutate, exit 2 on an "
                             "admission refusal)")
    _obs_common(vs)
    vs_group = vs.add_mutually_exclusive_group()
    vs_group.add_argument("--admit", action="store_true",
                          help="book fractional shares for a tenant "
                               "(needs --pod; mutate token)")
    vs_group.add_argument("--release", action="store_true",
                          help="release every share a tenant holds "
                               "(needs --pod; mutate token)")
    vs.add_argument("--namespace", default="default")
    vs.add_argument("--pod", default=None)
    vs.add_argument("--profile", default="balanced",
                    help="tenant serving profile: prefill, decode or "
                         "balanced (complementary profiles co-locate "
                         "first)")
    vs.add_argument("--chips", type=int, default=1,
                    help="how many chips to take a share of")
    vs.add_argument("--weight", type=int, default=50,
                    help="QoS weight per chip (1..VCHIP_WEIGHT_CAPACITY)")
    vs.add_argument("--rate-budget", type=int, default=0,
                    help="device-access token budget per chip "
                         "(0 = unmetered)")
    vs.add_argument("--chip", action="append", default=[],
                    metavar="UUID=NODE",
                    help="candidate chip for --admit (repeatable); the "
                         "packer also considers already-shared chips")
    vs.set_defaults(fn=cmd_shares)

    r = sub.add_parser("remove", help="hot-remove via a running master")
    r.add_argument("--master", required=True)
    r.add_argument("--namespace", default="default")
    r.add_argument("--pod", required=True)
    r.add_argument("--uuids", required=True, help="comma-separated")
    r.add_argument("--force", action="store_true")
    r.add_argument("--token", default=None,
                   help="master bearer token (default: "
                        "TPUMOUNTER_AUTH_TOKEN[_FILE])")
    r.set_defaults(fn=cmd_remove)
    return p


def main(argv: list[str] | None = None) -> int:
    init_logger()
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
