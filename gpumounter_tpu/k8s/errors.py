"""Typed Kubernetes API error hierarchy.

Before this module, every caller that cared WHY an API call failed
string-matched on raw status codes (`exc.status == 409 or
exc.status >= 500` in patch_pod_with_retry, ad-hoc `status != 503`
checks in subsystems). The degraded-mode control plane needs one shared
vocabulary — the ApiHealth state machine (k8s/health.py) classifies
failures by TYPE, the write-behind queue defers only on outage-shaped
errors, and retry layers decide from isinstance checks instead of
integer comparisons:

    ApiError                 any API-layer failure (carries .status)
      NotFoundError   404    the API ANSWERED: the object is gone
      ConflictError   409    the API ANSWERED: CAS/version conflict
      ServerError     5xx    the API is struggling (retriable)
        ApiTimeoutError 504  gateway/deadline timeout
        PartitionError  503  we cannot reach the API at all — raised
                             for transport-level failures (connection
                             refused/reset, TLS teardown, socket
                             timeouts) and by the fake's partition
                             simulator. Subclasses ServerError with
                             status 503 so every pre-existing handler
                             that caught ApiError-with-5xx still fires.

The split that matters for health classification: NotFound/Conflict
(and any 4xx) prove the API server is ALIVE — they are answers, not
outages. ServerError and below are evidence toward degraded/down.
"""

from __future__ import annotations

import socket


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"kubernetes api error {status}: {message}")
        self.status = status
        self.message = message


class NotFoundError(ApiError):
    def __init__(self, message: str = ""):
        super().__init__(404, message)


class ConflictError(ApiError):
    def __init__(self, message: str = ""):
        super().__init__(409, message)


class GoneError(ApiError):
    """410: the requested resourceVersion fell out of the API server's
    watch window (etcd compaction / the fake's trimmed backlog). The
    API ANSWERED — this is not outage evidence — but the watcher's
    cursor is unusable: re-LIST and re-open from the fresh version
    (store/watch.py's bounded relist)."""

    def __init__(self, message: str = ""):
        super().__init__(410, message)


class ServerError(ApiError):
    """5xx: the API server answered with a failure of its own. Safe to
    retry (the request may never have been applied) and evidence toward
    a degraded/down ApiHealth verdict."""

    def __init__(self, status: int, message: str = ""):
        super().__init__(status, message)


class ApiTimeoutError(ServerError):
    """504 gateway timeout, or a client-side deadline that expired while
    a request was in flight."""

    def __init__(self, message: str = "", status: int = 504):
        super().__init__(status, message)


class PartitionError(ServerError):
    """The API server is unreachable: connection refused/reset, the
    stream died mid-body, or the fake's set_partitioned simulator.
    Status 503 keeps every existing ApiError(5xx) handler working."""

    def __init__(self, message: str = ""):
        super().__init__(503, message)


def raise_for(status: int, body: str) -> None:
    """Map an HTTP status to the typed hierarchy (the REST client's and
    the fake's shared raise point)."""
    if status == 404:
        raise NotFoundError(body)
    if status == 409:
        raise ConflictError(body)
    if status == 410:
        raise GoneError(body)
    if status == 504:
        raise ApiTimeoutError(body)
    if status >= 500:
        raise ServerError(status, body)
    raise ApiError(status, body)


#: transport-level exception types that mean "could not reach / lost the
#: API server" — classified as PartitionError by classify_exception.
_TRANSPORT_EXCS = (ConnectionError, BrokenPipeError, socket.timeout,
                   TimeoutError, socket.gaierror, OSError)

#: OSError subclasses that are purely LOCAL failures (an unreadable
#: serviceaccount token file, a bad path) — never evidence the API
#: server is unreachable. Without this carve-out a kubelet rotating the
#: token underneath us would park the whole control plane in degraded
#: mode against a perfectly healthy API server.
_LOCAL_OS_EXCS = (FileNotFoundError, PermissionError, NotADirectoryError,
                  IsADirectoryError, FileExistsError, ProcessLookupError)


def _is_transport(exc: Exception) -> bool:
    return isinstance(exc, _TRANSPORT_EXCS) \
        and not isinstance(exc, _LOCAL_OS_EXCS)


def classify_exception(exc: Exception) -> ApiError:
    """Wrap an arbitrary client-layer exception into the typed
    hierarchy (already-typed errors pass through). Used by the
    health-tracking client so subscribers always see ApiError types."""
    if isinstance(exc, ApiError):
        return exc
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return ApiTimeoutError(str(exc) or type(exc).__name__)
    if _is_transport(exc):
        return PartitionError(f"{type(exc).__name__}: {exc}")
    # http.client's connection-state errors don't share a base with
    # ConnectionError; anything else transport-shaped lands here too.
    name = type(exc).__module__
    if name.startswith(("http.", "ssl")):
        return PartitionError(f"{type(exc).__name__}: {exc}")
    return ApiError(0, f"{type(exc).__name__}: {exc}")


def is_retriable(exc: Exception) -> bool:
    """May re-sending the same request succeed? Conflicts (merge-patch
    callers re-apply safely) and any 5xx/transport failure — never
    NotFound (the object is gone; retrying cannot help) and never other
    4xx (the request itself is wrong)."""
    if isinstance(exc, ConflictError):
        return True
    if isinstance(exc, ServerError):
        return True
    if isinstance(exc, ApiError):
        return exc.status >= 500
    return _is_transport(exc)


def is_outage(exc: Exception) -> bool:
    """Does this failure count as evidence the API server is degraded
    or unreachable (vs a perfectly healthy server answering 4xx)? The
    ApiHealth state machine's classification rule."""
    if isinstance(exc, ServerError):
        return True
    if isinstance(exc, ApiError):
        return exc.status >= 500 or exc.status == 0
    return _is_transport(exc) or \
        type(exc).__module__.startswith(("http.", "ssl"))
