"""ApiHealth: a per-endpoint health state machine for the Kubernetes API.

Every piece of the control plane round-trips through the API server —
intents, migration journals, shard leases, slave bookings — so an
API-server outage stalls or corrupts exactly the operations the system
exists to keep alive. The first step of riding one out is KNOWING:
instead of each subsystem discovering the outage through its own
timeout, one state machine per API endpoint classifies every call
outcome and publishes a verdict the whole process shares:

    healthy    calls succeed (or fail with 4xx answers — an answer
               proves the server is alive)
    degraded   `api_health_degraded_failures` consecutive outage-shaped
               failures (5xx / transport / timeout — k8s/errors.py
               is_outage). Subsystems park destructive work; reads may
               serve from cache.
    down       the failure streak has lasted `api_health_down_after_s`
               of continuous wall time. Mutating writes short-circuit
               into the write-behind queue without paying a doomed
               round trip.

Hysteresis: recovery requires `api_health_recovery_successes`
CONSECUTIVE successes — one lucky call mid-outage must not flip the
fleet back into destructive mode, fail again, flip back (flapping is
how a partial partition turns into a shrink/grow fight).

The instance is process-global per endpooint (one process talks to one
API server): `api_health()` returns the default endpoint's machine, and
`HealthTrackingKubeClient` feeds it from every call on the wrapped
client. Subscribers (the write-behind flusher, logs) get transition
callbacks OUTSIDE the lock.
"""

from __future__ import annotations

import threading
import time

from gpumounter_tpu.k8s.errors import classify_exception, is_outage
from gpumounter_tpu.k8s.client import KubeClient
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("k8s.health")

HEALTHY, DEGRADED, DOWN = "healthy", "degraded", "down"
_LEVEL = {HEALTHY: 0, DEGRADED: 1, DOWN: 2}

API_HEALTH_STATE = REGISTRY.gauge(
    "tpumounter_api_health_state",
    "Kubernetes API health verdict per endpoint "
    "(0=healthy, 1=degraded, 2=down)")
API_HEALTH_TRANSITIONS = REGISTRY.counter(
    "tpumounter_api_health_transitions_total",
    "ApiHealth state transitions by endpoint and new state")
API_CALL_FAILURES = REGISTRY.counter(
    "tpumounter_api_call_failures_total",
    "Outage-shaped Kubernetes API call failures by error class")


class _PlaneState:
    """One op plane's (read or write) streak accounting. An API
    partition is often ASYMMETRIC — writes fail while reads succeed
    through a stale LB, or vice versa — and a single shared streak
    would let the healthy plane's successes mask the broken one
    forever. Each plane judges itself; the endpoint verdict is the
    worst plane."""

    __slots__ = ("state", "failures", "successes", "first_failure_at",
                 "last_error")

    def __init__(self):
        self.state = HEALTHY
        self.failures = 0
        self.successes = 0
        self.first_failure_at: float | None = None
        self.last_error = ""


class ApiHealth:
    """One endpoint's state machine. Thread-safe; clock injectable."""

    PLANES = ("read", "write")

    def __init__(self, cfg=None, endpoint: str = "kube", now=None):
        from gpumounter_tpu.config import get_config
        cfg = cfg or get_config()
        self.endpoint = endpoint
        self.degraded_failures = max(
            1, int(cfg.api_health_degraded_failures))
        self.down_after_s = float(cfg.api_health_down_after_s)
        self.recovery_successes = max(
            1, int(cfg.api_health_recovery_successes))
        self._now = now or time.monotonic
        self._lock = threading.Lock()
        self._planes = {plane: _PlaneState() for plane in self.PLANES}
        self._state = HEALTHY            # worst plane (the verdict)
        self._since = self._now()        # when the verdict was entered
        self._transitions = 0
        #: callbacks fired (old_state, new_state) OUTSIDE the lock.
        self._subscribers: list = []
        API_HEALTH_STATE.set(0.0, endpoint=endpoint)

    # --- observation (fed by HealthTrackingKubeClient) ---

    def record_success(self, kind: str = "read") -> None:
        self._record(True, None, kind)

    def record_failure(self, exc: Exception, kind: str = "read") -> None:
        """An outage-shaped failure (callers pre-filter with is_outage;
        a 4xx answer should be recorded as SUCCESS — the server is
        alive)."""
        self._record(False, exc, kind)

    def observe(self, exc: Exception | None, kind: str = "read") -> None:
        """One call outcome on one plane ("read" or "write"): None =
        success; an exception is classified — outage-shaped failures
        count against the plane, 4xx answers count FOR it (the server
        answered)."""
        if exc is None or not is_outage(exc):
            self._record(True, None, kind)
        else:
            self._record(False, exc, kind)

    def _record(self, ok: bool, exc: Exception | None, kind: str) -> None:
        now = self._now()
        transition: tuple[str, str] | None = None
        with self._lock:
            plane = self._planes.get(kind) or self._planes["read"]
            if ok:
                plane.successes += 1
                plane.failures = 0
                plane.first_failure_at = None
                if plane.state != HEALTHY and \
                        plane.successes >= self.recovery_successes:
                    plane.state = HEALTHY
            else:
                typed = classify_exception(exc)
                plane.last_error = \
                    f"{type(typed).__name__}: {typed.message or typed}"
                API_CALL_FAILURES.inc(kind=type(typed).__name__)
                plane.successes = 0
                plane.failures += 1
                if plane.first_failure_at is None:
                    plane.first_failure_at = now
                if plane.failures >= self.degraded_failures:
                    if now - plane.first_failure_at >= self.down_after_s:
                        plane.state = DOWN
                    elif plane.state == HEALTHY:
                        plane.state = DEGRADED
            old = self._state
            worst = max((p.state for p in self._planes.values()),
                        key=_LEVEL.get)
            if worst != old:
                self._state = worst
                self._since = now
                self._transitions += 1
                transition = (old, worst)
                API_HEALTH_STATE.set(float(_LEVEL[worst]),
                                     endpoint=self.endpoint)
                API_HEALTH_TRANSITIONS.inc(endpoint=self.endpoint,
                                           state=worst)
            subscribers = list(self._subscribers) if transition else []
            last_error = self._last_error_locked()
        if transition:
            old_state, new_state = transition
            log = logger.warning if new_state != HEALTHY else logger.info
            log("api endpoint %r %s -> %s (%s)", self.endpoint,
                old_state, new_state,
                last_error if new_state != HEALTHY else "recovered")
            for fn in subscribers:
                try:
                    fn(old_state, new_state)
                except Exception:  # noqa: BLE001 — advisory hooks
                    logger.exception("api-health subscriber failed")

    def _last_error_locked(self) -> str:
        for plane in self._planes.values():
            if plane.state != HEALTHY and plane.last_error:
                return plane.last_error
        for plane in self._planes.values():
            if plane.last_error:
                return plane.last_error
        return ""

    # --- verdicts ---

    def state(self) -> str:
        with self._lock:
            return self._state

    def plane_state(self, kind: str) -> str:
        with self._lock:
            plane = self._planes.get(kind)
            return plane.state if plane is not None else HEALTHY

    def ok(self) -> bool:
        """True only when every plane is healthy — the gate destructive
        subsystem actions check before acting on API-derived state (a
        working read plane is no license to mutate when writes are
        black-holed, and stale writes are no license to trust reads)."""
        with self._lock:
            return self._state == HEALTHY

    def is_down(self) -> bool:
        with self._lock:
            return self._state == DOWN

    def write_plane_ok(self) -> bool:
        """True while writes still land — the write-behind queue defers
        only when THIS plane is broken (a read-side partition must not
        reroute perfectly deliverable writes through the queue)."""
        with self._lock:
            return self._planes["write"].state == HEALTHY

    def subscribe(self, fn) -> None:
        """fn(old_state, new_state) on every overall transition,
        outside the lock (a slow subscriber cannot block
        observation). Idempotent by identity so process-global hooks
        (the flight recorder) can re-install themselves freely."""
        with self._lock:
            if not any(s is fn for s in self._subscribers):
                self._subscribers.append(fn)

    def payload(self) -> dict:
        with self._lock:
            return {
                "endpoint": self.endpoint,
                "state": self._state,
                "sinceS": round(self._now() - self._since, 3),
                "consecutiveFailures": max(
                    p.failures for p in self._planes.values()),
                "transitions": self._transitions,
                "lastError": self._last_error_locked(),
                "planes": {
                    kind: {
                        "state": plane.state,
                        "consecutiveFailures": plane.failures,
                        "consecutiveSuccesses": plane.successes,
                        "lastError": plane.last_error,
                    } for kind, plane in self._planes.items()},
                "config": {
                    "degradedFailures": self.degraded_failures,
                    "downAfterS": self.down_after_s,
                    "recoverySuccesses": self.recovery_successes,
                },
            }

    def reset(self) -> None:
        """Test hook (conftest): back to a pristine healthy machine."""
        with self._lock:
            self._planes = {plane: _PlaneState()
                            for plane in self.PLANES}
            self._state = HEALTHY
            self._since = self._now()
            self._transitions = 0
            self._subscribers = []
            API_HEALTH_STATE.set(0.0, endpoint=self.endpoint)


# --- the process-global per-endpoint registry ---

_registry_lock = threading.Lock()
_instances: dict[str, ApiHealth] = {}


def api_health(endpoint: str = "kube", cfg=None) -> ApiHealth:
    """The process-wide ApiHealth machine for one endpoint (a process
    talks to one API server, so master routes, worker ops, the store
    and every subsystem share a single verdict)."""
    with _registry_lock:
        instance = _instances.get(endpoint)
        if instance is None:
            instance = ApiHealth(cfg=cfg, endpoint=endpoint)
            _instances[endpoint] = instance
        return instance


def reset_all() -> None:
    """Test hook: drop every endpoint machine (conftest runs this
    between tests so one test's simulated outage cannot leak a
    degraded verdict into the next)."""
    with _registry_lock:
        for instance in _instances.values():
            instance.reset()
        _instances.clear()


class HealthTrackingKubeClient(KubeClient):
    """Delegating KubeClient that feeds every call outcome into an
    ApiHealth machine. Unknown attributes (fake-only test helpers like
    set_partitioned / create_node) pass through to the inner client, so
    wrapping is transparent to tests holding the wrapper."""

    def __init__(self, inner: KubeClient, health: ApiHealth | None = None):
        self.inner = inner
        self.health = health or api_health()

    def __getattr__(self, name):
        # Only called for attributes not defined here: fake-client test
        # helpers, ad-hoc extensions. Not health-tracked (they are not
        # API calls in production).
        return getattr(self.inner, name)

    def _call(self, kind: str, name: str, *args, **kwargs):
        try:
            out = getattr(self.inner, name)(*args, **kwargs)
        except NotImplementedError:
            raise  # capability gap, not an API outcome
        except Exception as exc:  # noqa: BLE001 — classification boundary
            self.health.observe(exc, kind)
            raise
        self.health.observe(None, kind)
        return out

    # --- the KubeClient surface, call-tracked per plane ---

    def get_pod(self, namespace, name):
        return self._call("read", "get_pod", namespace, name)

    def create_pod(self, namespace, manifest):
        return self._call("write", "create_pod", namespace, manifest)

    def delete_pod(self, namespace, name, grace_period_seconds=0):
        return self._call("write", "delete_pod", namespace, name,
                          grace_period_seconds=grace_period_seconds)

    def list_pods(self, namespace=None, label_selector="",
                  field_selector=""):
        return self._call("read", "list_pods", namespace,
                          label_selector=label_selector,
                          field_selector=field_selector)

    def list_pods_with_rv(self, namespace=None, label_selector="",
                          field_selector=""):
        return self._call("read", "list_pods_with_rv", namespace,
                          label_selector=label_selector,
                          field_selector=field_selector)

    def patch_pod(self, namespace, name, patch):
        return self._call("write", "patch_pod", namespace, name, patch)

    def watch_pods(self, namespace, *, label_selector="",
                   field_selector="", timeout_s=60.0,
                   resource_version=""):
        # The OPEN is tracked (it is the call that fails during an
        # outage); the stream itself is consumed by the caller.
        return self._call("read", "watch_pods", namespace,
                          label_selector=label_selector,
                          field_selector=field_selector,
                          timeout_s=timeout_s,
                          resource_version=resource_version)

    def create_event(self, namespace, manifest):
        return self._call("write", "create_event", namespace, manifest)

    def get_lease(self, namespace, name):
        return self._call("read", "get_lease", namespace, name)

    def create_lease(self, namespace, manifest):
        return self._call("write", "create_lease", namespace, manifest)

    def update_lease(self, namespace, name, manifest):
        return self._call("write", "update_lease", namespace, name,
                          manifest)

    def get_node(self, name):
        return self._call("read", "get_node", name)

    def list_nodes(self):
        return self._call("read", "list_nodes")


def wrap_health(kube: KubeClient,
                health: ApiHealth | None = None) -> KubeClient:
    """Idempotent wrap: an already-tracking client is returned as-is
    (MasterApp and the worker service both wrap defensively)."""
    if isinstance(kube, HealthTrackingKubeClient):
        return kube
    return HealthTrackingKubeClient(kube, health)
