"""Minimal Kubernetes REST client (stdlib only; pyyaml needed only for
the out-of-cluster kubeconfig path).

Replaces the reference's client-go usage (pkg/config/config.go:30-45 — a
sync.Once in-cluster clientset). The image has no `kubernetes` Python package
and installs are forbidden, so this speaks the API directly:

  * in-cluster auth: service-account bearer token + cluster CA
    (/var/run/secrets/kubernetes.io/serviceaccount/...)
  * pods: get / create / delete / list (label & field selectors)
  * watch: chunked JSON event stream — used instead of the reference's
    unbounded phase busy-polls (allocator.go:246-317, a SURVEY §3 hot loop)

All methods return/accept raw API JSON dicts (see k8s.types.Pod wrapper).
"""

from __future__ import annotations

import abc
import json
import os
import socket
import ssl
import threading
import time
import urllib.parse
from collections.abc import Iterator
from typing import Any

from gpumounter_tpu.faults import failpoints

# The typed error hierarchy lives in k8s/errors.py (shared with the
# ApiHealth classifier and the write-behind queue); re-exported here
# because every subsystem historically imported it from this module.
from gpumounter_tpu.k8s.errors import (  # noqa: F401 — re-exports
    ApiError,
    ApiTimeoutError,
    ConflictError,
    GoneError,
    NotFoundError,
    PartitionError,
    ServerError,
    is_retriable,
    raise_for,
)
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("k8s")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

_raise_for = raise_for  # back-compat alias (failpoint injection helper)


def inject_write_fault(op: str, namespace: str, name: str) -> None:
    """Failpoint hook shared by the real REST client and the test fake so
    the chaos harness can inject API-server behavior on either path:

      k8s.<op>           delay / error / crash at the write
      k8s.<op>.status    return(409) → ConflictError, return(5xx) → ApiError

    ops in use: patch_pod, create_pod, delete_pod."""
    failpoints.fire(f"k8s.{op}", namespace=namespace, name=name)
    status = failpoints.value(f"k8s.{op}.status", None,
                              namespace=namespace, name=name)
    if status is not None:
        _raise_for(int(status),
                   f"failpoint k8s.{op}.status on {namespace}/{name}")


def patch_pod_with_retry(kube: "KubeClient", namespace: str, name: str,
                         patch: dict, attempts: int = 3,
                         base_s: float = 0.1, cap_s: float = 2.0) -> dict:
    """Bounded-retry merge-patch for control-plane writers (the elastic
    reconciler's heal marker, the migration journal/phase stamps).

    A merge-patch carries no resourceVersion, so re-applying it after a
    409 conflict or a transient 5xx is safe — the writes retried here are
    self-contained annotation updates, last-writer-wins by design. 404
    propagates immediately (the pod is gone; retrying cannot help), as
    does the final failure after `attempts` tries. The transport deadline
    per attempt is the REST client's per-request timeout."""
    from gpumounter_tpu.rpc.resilience import RetryPolicy  # stdlib-only
    policy = RetryPolicy(max_attempts=max(1, attempts), base_s=base_s,
                         cap_s=cap_s)
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return kube.patch_pod(namespace, name, patch)
        except NotFoundError:
            raise
        except ApiError as exc:
            # Typed retriability (k8s/errors.py): Conflict (merge-patch
            # re-applies safely) and ServerError/transport only.
            if not is_retriable(exc) or attempt >= policy.max_attempts:
                raise
            delay = policy.delay_for(attempt)
            logger.warning(
                "patch of %s/%s failed (%s, attempt %d/%d); retrying in "
                "%.2fs", namespace, name, exc.status, attempt,
                policy.max_attempts, delay)
            time.sleep(delay)
    raise AssertionError("unreachable")


class KubeClient(abc.ABC):
    """The surface both the real REST client and the test fake implement."""

    @abc.abstractmethod
    def get_pod(self, namespace: str, name: str) -> dict: ...

    @abc.abstractmethod
    def create_pod(self, namespace: str, manifest: dict) -> dict: ...

    @abc.abstractmethod
    def delete_pod(self, namespace: str, name: str, grace_period_seconds: int = 0) -> None: ...

    @abc.abstractmethod
    def list_pods(self, namespace: str | None = None, label_selector: str = "",
                  field_selector: str = "") -> list[dict]: ...

    @abc.abstractmethod
    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        """RFC 7386 merge-patch: dicts merge recursively, an explicit None
        deletes the key. Used to persist declarative state (elastic intent
        annotations) on pods so it survives master restarts."""
        ...

    @abc.abstractmethod
    def watch_pods(self, namespace: str, *, label_selector: str = "",
                   field_selector: str = "", timeout_s: float = 60.0,
                   resource_version: str = "") -> Iterator[tuple[str, dict]]:
        """Yield (event_type, pod_json) until timeout. Types:
        ADDED/MODIFIED/DELETED. namespace="" watches every namespace.
        resource_version resumes from that point in the event history;
        a version that already fell out of the server's watch window
        raises GoneError (the caller re-LISTs and re-opens — the
        informer protocol, store/watch.py)."""
        ...

    def list_pods_with_rv(self, namespace: str | None = None,
                          label_selector: str = "",
                          field_selector: str = "",
                          ) -> tuple[list[dict], str]:
        """LIST plus the collection resourceVersion the list was taken
        at — the informer's resume cursor. Default: plain list with an
        empty cursor (watch-from-now; backends that can do better
        override)."""
        return self.list_pods(namespace, label_selector=label_selector,
                              field_selector=field_selector), ""

    def create_event(self, namespace: str, manifest: dict) -> dict:
        """Post a core/v1 Event. Best-effort surface; default no-op so
        non-cluster deployments (CLI local mode) need nothing."""
        return {}

    # --- coordination.k8s.io/v1 Leases (shard leader election) ---
    #
    # The sharded-master plane (master/shard.py) elects one owner per
    # node shard through standard Lease objects, exactly like
    # kube-controller-manager leader election: acquire = create (or
    # replace an expired holder), renew = replace with a fresh
    # renewTime, and every replace carries the read resourceVersion so
    # two replicas racing for the same lease get a clean ConflictError
    # instead of a silent last-writer-wins.

    def get_lease(self, namespace: str, name: str) -> dict:
        raise NotImplementedError(
            f"{type(self).__name__} does not support leases")

    def create_lease(self, namespace: str, manifest: dict) -> dict:
        raise NotImplementedError(
            f"{type(self).__name__} does not support leases")

    def update_lease(self, namespace: str, name: str,
                     manifest: dict) -> dict:
        """Full replace (PUT). The manifest's metadata.resourceVersion
        must match the server's current one; raises ConflictError when
        another writer got there first — the CAS the shard manager's
        acquire/renew race safety rests on."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support leases")

    # --- core/v1 Nodes (recovery plane: node-readiness signal) ---
    #
    # The recovery controller (gpumounter_tpu/recovery/) confirms node
    # death by combining worker liveness with the Node object's Ready
    # condition — a crashed worker on a Ready node is left to ledger
    # replay, never evacuated. Default raises NotImplementedError so
    # non-cluster backends degrade to "no readiness signal" cleanly.

    def get_node(self, name: str) -> dict:
        raise NotImplementedError(
            f"{type(self).__name__} does not support nodes")

    def list_nodes(self) -> list[dict]:
        raise NotImplementedError(
            f"{type(self).__name__} does not support nodes")

    # --- composed helper used by the allocator ---

    def wait_for_pod(self, namespace: str, name: str, predicate,
                     timeout_s: float) -> dict | None:
        """Wait until predicate(pod_json) is truthy; None on timeout.

        Watch-driven with a list fallback; replaces the reference's zero-sleep
        busy-poll (checkCreateState/checkDeleteState, allocator.go:246-317).
        For "wait for deletion" predicates, pass predicate(None)->True on the
        DELETED event / absent pod.
        """
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            # Subscribe FIRST (watch_pods connects eagerly), then check
            # current state: an event landing between the check and the
            # subscription can then never be lost — it is already queued
            # on the open watch.
            watch = None
            try:
                try:
                    watch = self.watch_pods(
                        namespace,
                        field_selector=f"metadata.name={name}",
                        timeout_s=min(remaining, 30.0))
                except ApiError as exc:
                    logger.warning("watch failed (%s); falling back to poll",
                                   exc)
                    time.sleep(min(1.0, max(0.0,
                                            deadline - time.monotonic())))
                try:
                    pod = self.get_pod(namespace, name)
                except NotFoundError:
                    pod = None
                if predicate(pod):
                    return pod if pod is not None else {"__deleted__": True}
                if watch is None:
                    continue
                try:
                    for etype, obj in watch:
                        if etype == "DELETED":
                            if predicate(None):
                                return {"__deleted__": True}
                            continue
                        if predicate(obj):
                            return obj
                        if time.monotonic() >= deadline:
                            return None
                except ApiError as exc:
                    logger.warning("watch stream failed (%s); retrying", exc)
                    time.sleep(min(1.0, max(0.0,
                                            deadline - time.monotonic())))
                else:
                    # Watch window closed without a match (apiserver/proxy
                    # may end streams immediately): don't degenerate into a
                    # zero-sleep reconnect loop.
                    time.sleep(min(0.2, max(0.0,
                                            deadline - time.monotonic())))
            finally:
                close = getattr(watch, "close", None)
                if close is not None:
                    close()


class RestKubeClient(KubeClient):
    #: request exceptions that mean "the kept-alive connection went
    #: stale under us" (apiserver idle close, LB reset, TLS teardown) —
    #: safe to reconnect-and-resend once for idempotent methods.
    _STALE_RETRY_METHODS = frozenset({"GET", "PUT", "DELETE", "PATCH"})

    def __init__(self, host: str, port: int, token: str,
                 ca_file: str | None = None, verify: bool = True):
        self.host = host
        self.port = port
        self.token = token
        self.ctx = ssl.create_default_context(cafile=ca_file) if verify else None
        if self.ctx is None:
            self.ctx = ssl.create_default_context()
            self.ctx.check_hostname = False
            self.ctx.verify_mode = ssl.CERT_NONE
        # One kept-alive connection per calling thread (http.client
        # connections are not thread-safe; a lock would serialize every
        # API call through one socket instead).
        self._conn_local = threading.local()

    # --- low-level ---

    def _connect(self, timeout: float = 30.0):
        import http.client
        return http.client.HTTPSConnection(self.host, self.port,
                                           context=self.ctx,
                                           timeout=timeout)

    def _request(self, method: str, path: str, query: dict | None = None,
                 body: dict | None = None, timeout: float = 30.0,
                 content_type: str = "application/json"):
        """Dedicated-connection request: the caller owns (conn, resp).
        Used by the watch stream, whose connection outlives the call and
        must never be shared with the pooled request path."""
        qs = ("?" + urllib.parse.urlencode(query)) if query else ""
        conn = self._connect(timeout)
        headers = self._headers(body, content_type)
        payload = json.dumps(body) if body is not None else None
        conn.request(method, path + qs, body=payload, headers=headers)
        return conn, conn.getresponse()

    def _headers(self, body, content_type: str) -> dict:
        headers = {
            "Authorization": f"Bearer {self.token}",
            "Accept": "application/json",
        }
        if body is not None:
            headers["Content-Type"] = content_type
        return headers

    def _stale_exceptions(self) -> tuple:
        import http.client
        return (http.client.NotConnected, http.client.CannotSendRequest,
                http.client.BadStatusLine, http.client.ImproperConnectionState,
                ConnectionError, BrokenPipeError, ssl.SSLEOFError)

    def _drop_pooled(self) -> None:
        conn = getattr(self._conn_local, "conn", None)
        self._conn_local.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — teardown of a dead socket
                pass

    def _json(self, method: str, path: str, query: dict | None = None,
              body: dict | None = None,
              content_type: str = "application/json") -> dict:
        """Keep-alive request: reuses this thread's cached connection
        (one TCP+TLS handshake per thread, not per API call — the
        reference-era shape dialed fresh for every GET/POST, a SURVEY §3
        control-plane tax). A connection gone stale mid-reuse is rebuilt
        and the request re-sent once — but only for idempotent methods;
        a POST whose first send may have landed must surface the error
        (its callers' retry layers own that decision)."""
        qs = ("?" + urllib.parse.urlencode(query)) if query else ""
        headers = self._headers(body, content_type)
        payload = json.dumps(body) if body is not None else None
        stale_excs = self._stale_exceptions()
        last_exc: Exception | None = None
        for attempt in (1, 2):
            conn = getattr(self._conn_local, "conn", None)
            fresh = conn is None
            if fresh:
                conn = self._connect()
                self._conn_local.conn = conn
            sent = False
            try:
                conn.request(method, path + qs, body=payload,
                             headers=headers)
                sent = True
                resp = conn.getresponse()
            except stale_excs as exc:
                self._drop_pooled()
                last_exc = exc
                # Send-phase failure: the request never reached the
                # server, so resending is safe for ANY method (POST
                # included). Response-phase failure is ambiguous — the
                # server may have processed the request — so only
                # idempotent methods retry there. A brand-new connection
                # failing is a real error either way, not staleness.
                retriable = (not sent
                             or method in self._STALE_RETRY_METHODS)
                if fresh or not retriable or attempt == 2:
                    raise
                logger.debug("kept-alive connection stale (%s); "
                             "reconnecting", exc)
                continue
            except Exception:
                self._drop_pooled()
                raise
            try:
                data = resp.read().decode("utf-8", "replace")
            except Exception:
                # Half-read responses poison connection reuse.
                self._drop_pooled()
                raise
            if resp.status >= 400:
                _raise_for(resp.status, data)
            return json.loads(data) if data else {}
        raise last_exc  # pragma: no cover — loop always returns/raises

    # --- pods ---

    def get_pod(self, namespace: str, name: str) -> dict:
        return self._json("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def create_pod(self, namespace: str, manifest: dict) -> dict:
        inject_write_fault("create_pod", namespace,
                           manifest.get("metadata", {}).get("name", ""))
        return self._json("POST", f"/api/v1/namespaces/{namespace}/pods", body=manifest)

    def delete_pod(self, namespace: str, name: str, grace_period_seconds: int = 0) -> None:
        try:
            # Inject inside the try: a simulated 404 must behave exactly
            # like a real one (delete-of-missing is a silent no-op).
            inject_write_fault("delete_pod", namespace, name)
            self._json("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}",
                       query={"gracePeriodSeconds": grace_period_seconds})
        except NotFoundError:
            pass

    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        inject_write_fault("patch_pod", namespace, name)
        return self._json("PATCH",
                          f"/api/v1/namespaces/{namespace}/pods/{name}",
                          body=patch,
                          content_type="application/merge-patch+json")

    def create_event(self, namespace: str, manifest: dict) -> dict:
        return self._json("POST", f"/api/v1/namespaces/{namespace}/events",
                          body=manifest)

    # --- leases (coordination.k8s.io/v1) ---

    _LEASE_BASE = "/apis/coordination.k8s.io/v1/namespaces"

    def get_lease(self, namespace: str, name: str) -> dict:
        return self._json("GET",
                          f"{self._LEASE_BASE}/{namespace}/leases/{name}")

    def create_lease(self, namespace: str, manifest: dict) -> dict:
        inject_write_fault("create_lease", namespace,
                           manifest.get("metadata", {}).get("name", ""))
        return self._json("POST", f"{self._LEASE_BASE}/{namespace}/leases",
                          body=manifest)

    def update_lease(self, namespace: str, name: str,
                     manifest: dict) -> dict:
        inject_write_fault("update_lease", namespace, name)
        return self._json("PUT",
                          f"{self._LEASE_BASE}/{namespace}/leases/{name}",
                          body=manifest)

    # --- core/v1 Nodes ---

    def get_node(self, name: str) -> dict:
        return self._json("GET", f"/api/v1/nodes/{name}")

    def list_nodes(self) -> list[dict]:
        return self._json("GET", "/api/v1/nodes").get("items", [])

    def list_pods(self, namespace: str | None = None, label_selector: str = "",
                  field_selector: str = "") -> list[dict]:
        return self._list_pods_raw(namespace, label_selector,
                                   field_selector).get("items", [])

    def list_pods_with_rv(self, namespace: str | None = None,
                          label_selector: str = "",
                          field_selector: str = "",
                          ) -> tuple[list[dict], str]:
        doc = self._list_pods_raw(namespace, label_selector,
                                  field_selector)
        return doc.get("items", []), \
            str(doc.get("metadata", {}).get("resourceVersion", "") or "")

    def _list_pods_raw(self, namespace: str | None, label_selector: str,
                       field_selector: str) -> dict:
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        query: dict[str, Any] = {}
        if label_selector:
            query["labelSelector"] = label_selector
        if field_selector:
            query["fieldSelector"] = field_selector
        return self._json("GET", path, query=query)

    def watch_pods(self, namespace: str, *, label_selector: str = "",
                   field_selector: str = "", timeout_s: float = 60.0,
                   resource_version: str = "") -> Iterator[tuple[str, dict]]:
        query: dict[str, Any] = {"watch": "true",
                                 "timeoutSeconds": max(1, int(timeout_s))}
        if label_selector:
            query["labelSelector"] = label_selector
        if field_selector:
            query["fieldSelector"] = field_selector
        if resource_version:
            query["resourceVersion"] = resource_version
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")  # "" = all namespaces (informer)
        # Open the connection EAGERLY (before the generator is consumed):
        # wait_for_pod depends on watch-then-recheck ordering to avoid
        # losing events raised between its state check and the watch start.
        conn, resp = self._request("GET", path, query,
                                   timeout=timeout_s + 10.0)
        if resp.status >= 400:
            body = resp.read().decode("utf-8", "replace")
            conn.close()
            _raise_for(resp.status, body)
        return _WatchStream(conn, resp)


class _WatchStream:
    """Iterator over watch events that owns the HTTP connection: `close()`
    releases it even when the stream is never consumed (generators only run
    their finally once started)."""

    def __init__(self, conn, resp):
        self._conn = conn
        self._resp = resp
        self._buf = b""
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> tuple[str, dict]:
        if self._done:
            raise StopIteration
        while True:
            while b"\n" in self._buf:
                line, _, self._buf = self._buf.partition(b"\n")
                if not line.strip():
                    continue
                event = json.loads(line)
                etype = event.get("type", "")
                obj = event.get("object", {})
                if etype == "ERROR":
                    # The API server reports an expired resourceVersion
                    # as an in-stream ERROR Status with code 410; the
                    # informer must re-LIST, not keep consuming.
                    self.close()
                    code = int(obj.get("code", 0) or 0)
                    if code == 410:
                        raise GoneError(obj.get("message", "watch expired"))
                    _raise_for(code or 500, obj.get("message", ""))
                return etype, obj
            try:
                chunk = self._resp.read1(65536)
            except (socket.timeout, TimeoutError):
                chunk = b""
            if not chunk:
                self.close()
                raise StopIteration

            self._buf += chunk

    def close(self) -> None:
        if not self._done:
            self._done = True
            self._conn.close()


def kubeconfig_client(path: str | None = None,
                      context: str | None = None) -> RestKubeClient:
    """Build a client from a kubeconfig file (out-of-cluster path).

    The reference stubs this out — `kubeConfigPath` is a placeholder
    string and inCluster is hardwired true (config.go:20,31) — so its
    binaries only ever run inside the cluster. This loader makes the
    daemons and CLI usable from a laptop against kind/minikube/GKE:

      * path: explicit arg > $KUBECONFIG > ~/.kube/config
      * context: explicit arg > current-context
      * cluster: server URL, certificate-authority[-data],
        insecure-skip-tls-verify
      * user: token / token-file bearer auth, or client-certificate[-data]
        + client-key[-data] mTLS (the kind default). exec plugins are
        refused with an actionable error — running arbitrary
        credential helpers is out of scope for a privileged daemon.
    """
    import base64
    import shutil
    import tempfile

    import yaml

    path = path or os.environ.get("KUBECONFIG") \
        or os.path.expanduser("~/.kube/config")
    with open(path, encoding="utf-8") as f:
        cfg = yaml.safe_load(f) or {}

    def _by_name(section: str, name: str) -> dict:
        for entry in cfg.get(section, []):
            if entry.get("name") == name:
                return entry
        raise ValueError(f"kubeconfig {path}: no {section!r} entry "
                         f"named {name!r}")

    ctx_name = context or cfg.get("current-context")
    if not ctx_name:
        raise ValueError(f"kubeconfig {path}: no current-context and no "
                         f"context argument given")
    ctx = _by_name("contexts", ctx_name).get("context", {})
    cluster = _by_name("clusters", ctx.get("cluster", "")).get("cluster", {})
    user = _by_name("users", ctx.get("user", "")).get("user", {})

    server = cluster.get("server", "")
    parsed = urllib.parse.urlsplit(server)
    if parsed.scheme != "https":
        raise ValueError(f"kubeconfig cluster server must be https, "
                         f"got {server!r}")
    host = parsed.hostname or ""
    port = parsed.port or 443

    ca_file = cluster.get("certificate-authority") or None
    ca_data = None
    if cluster.get("certificate-authority-data"):
        # cadata goes straight into the SSL context — no key/cert
        # material is ever written to disk for the *-data variants.
        ca_data = base64.b64decode(
            cluster["certificate-authority-data"]).decode()
        ca_file = None
    verify = not cluster.get("insecure-skip-tls-verify", False)

    if "exec" in user:
        raise ValueError(
            "kubeconfig user uses an exec credential plugin; this client "
            "does not run external helpers — extract a token (e.g. "
            "`kubectl create token ...`) and use the token field")
    token = user.get("token", "")
    if not token and user.get("tokenFile"):
        with open(user["tokenFile"], encoding="utf-8") as f:
            token = f.read().strip()
    has_cert = bool(user.get("client-certificate")
                    or user.get("client-certificate-data"))
    has_key = bool(user.get("client-key") or user.get("client-key-data"))
    if not token and not has_cert:
        raise ValueError(
            f"kubeconfig user {ctx.get('user')!r} has neither a token nor "
            f"a client certificate; cannot authenticate")
    if has_cert and not has_key:
        raise ValueError("client-certificate given without client-key")

    client = RestKubeClient(host, port, token,
                            ca_file=ca_file if verify else None,
                            verify=verify)
    if verify and ca_data:
        client.ctx.load_verify_locations(cadata=ca_data)
    if has_cert:
        # load_cert_chain wants file paths and reads them eagerly, so
        # inline *-data key material only touches disk inside a private
        # temp dir that is removed before returning.
        tmp = None
        try:
            cert_path = user.get("client-certificate")
            key_path = user.get("client-key")
            if user.get("client-certificate-data") or \
                    user.get("client-key-data"):
                tmp = tempfile.mkdtemp(prefix="tpumounter-kc-")
                os.chmod(tmp, 0o700)
                if user.get("client-certificate-data"):
                    cert_path = os.path.join(tmp, "client.crt")
                    with open(cert_path, "wb") as f:
                        f.write(base64.b64decode(
                            user["client-certificate-data"]))
                if user.get("client-key-data"):
                    key_path = os.path.join(tmp, "client.key")
                    with open(key_path, "wb") as f:
                        f.write(base64.b64decode(user["client-key-data"]))
                    os.chmod(key_path, 0o600)
            client.ctx.load_cert_chain(cert_path, key_path)
        finally:
            if tmp is not None:
                shutil.rmtree(tmp, ignore_errors=True)
    logger.info("kubeconfig client: context=%s server=%s auth=%s",
                ctx_name, server, "mtls" if has_cert else "token")
    return client


def default_client() -> RestKubeClient:
    """In-cluster when the service-account token exists (the deployed
    daemons), kubeconfig otherwise (laptop / dev)."""
    token_file = os.environ.get("TPUMOUNTER_TOKEN_FILE",
                                os.path.join(SA_DIR, "token"))
    if os.path.exists(token_file):
        return in_cluster_client()
    try:
        return kubeconfig_client()
    except Exception as exc:
        # In a pod, landing here usually means the SA token was never
        # mounted (automountServiceAccountToken: false) — name THAT
        # problem instead of surfacing a kubeconfig/yaml error from a
        # fallback path container images don't even support.
        raise RuntimeError(
            f"no service-account token at {token_file} and the "
            f"kubeconfig fallback failed ({type(exc).__name__}: {exc}); "
            f"in-cluster: check automountServiceAccountToken / the "
            f"projected token volume; on a laptop: check $KUBECONFIG "
            f"(pyyaml required)") from exc


def in_cluster_client() -> RestKubeClient:
    """Build a client from the pod's service account.

    Reference hardwires inCluster := true (config.go:31); we also honour
    KUBERNETES_SERVICE_HOST/PORT overrides for out-of-cluster testing with a
    token file via TPUMOUNTER_TOKEN_FILE.
    """
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = int(os.environ.get("KUBERNETES_SERVICE_PORT", "443"))
    token_file = os.environ.get("TPUMOUNTER_TOKEN_FILE", os.path.join(SA_DIR, "token"))
    ca_file = os.environ.get("TPUMOUNTER_CA_FILE", os.path.join(SA_DIR, "ca.crt"))
    with open(token_file) as f:
        token = f.read().strip()
    if os.path.exists(ca_file):
        return RestKubeClient(host, port, token, ca_file=ca_file, verify=True)
    # Never silently downgrade TLS: the bearer token would travel over an
    # unverified channel. Explicit opt-in only (dev clusters).
    if os.environ.get("TPUMOUNTER_INSECURE_SKIP_TLS_VERIFY") == "1":
        logger.warning("CA file %s missing; TLS verification DISABLED by "
                       "TPUMOUNTER_INSECURE_SKIP_TLS_VERIFY=1", ca_file)
        return RestKubeClient(host, port, token, verify=False)
    raise FileNotFoundError(
        f"cluster CA not found at {ca_file}; set TPUMOUNTER_CA_FILE or "
        "TPUMOUNTER_INSECURE_SKIP_TLS_VERIFY=1 to opt out of verification")
