"""Best-effort Kubernetes Events on pods.

The reference writes logs only (SURVEY.md §5 "no events on the Pod");
here every control-plane component surfaces outcomes where operators
actually look — `kubectl describe pod`. One shared manifest builder so
the worker, the elastic reconciler, the slice coordinator, and the
migration orchestrator emit the same shape under different `source`
components. Failures are logged and swallowed: events are advisory and
must never fail the operation they describe.
"""

from __future__ import annotations

import secrets
import time

from gpumounter_tpu.k8s.types import Pod
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("k8s.events")


def post_pod_event(kube, pod: Pod, reason: str, message: str,
                   event_type: str = "Normal",
                   component: str = "tpumounter") -> None:
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    manifest = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "name": f"{pod.name[:200]}.tpumounter.{secrets.token_hex(4)}",
            "namespace": pod.namespace,
        },
        "involvedObject": {"kind": "Pod", "name": pod.name,
                           "namespace": pod.namespace, "uid": pod.uid},
        "reason": reason,
        "message": message[:1024],
        "type": event_type,
        "source": {"component": component},
        "firstTimestamp": ts,
        "lastTimestamp": ts,
        "count": 1,
    }
    try:
        kube.create_event(pod.namespace, manifest)
        posted = True
    except Exception as exc:  # noqa: BLE001 — events are advisory
        posted = False
        logger.debug("event post failed: %s", exc)
    # The flight recorder's timeline keeps the Event even when the API
    # post failed — during an outage the timeline is exactly where an
    # operator will look for what the cluster never got to see.
    from gpumounter_tpu.obs.flight import FLIGHT
    FLIGHT.record("event", f"{reason}: {message}"[:240],
                  namespace=pod.namespace, pod=pod.name, reason=reason,
                  event_type=event_type, component=component,
                  posted=posted)
