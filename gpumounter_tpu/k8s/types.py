"""Thin typed view over Kubernetes Pod JSON.

The reference uses client-go's corev1.Pod structs; we carry raw API JSON
(dicts) end-to-end and wrap them in this accessor class where convenient.
"""

from __future__ import annotations

from typing import Any


class Pod:
    def __init__(self, obj: dict):
        self.obj = obj

    # --- metadata ---
    @property
    def name(self) -> str:
        return self.obj.get("metadata", {}).get("name", "")

    @property
    def namespace(self) -> str:
        return self.obj.get("metadata", {}).get("namespace", "")

    @property
    def uid(self) -> str:
        return self.obj.get("metadata", {}).get("uid", "")

    @property
    def labels(self) -> dict[str, str]:
        return self.obj.get("metadata", {}).get("labels", {}) or {}

    @property
    def annotations(self) -> dict[str, str]:
        return self.obj.get("metadata", {}).get("annotations", {}) or {}

    @property
    def owner_references(self) -> list[dict]:
        return self.obj.get("metadata", {}).get("ownerReferences", []) or []

    # --- spec ---
    @property
    def node_name(self) -> str:
        return self.obj.get("spec", {}).get("nodeName", "")

    @property
    def containers(self) -> list[dict]:
        return self.obj.get("spec", {}).get("containers", []) or []

    # --- status ---
    @property
    def phase(self) -> str:
        return self.obj.get("status", {}).get("phase", "")

    @property
    def pod_ip(self) -> str:
        return self.obj.get("status", {}).get("podIP", "")

    @property
    def container_statuses(self) -> list[dict]:
        return self.obj.get("status", {}).get("containerStatuses", []) or []

    @property
    def conditions(self) -> list[dict]:
        return self.obj.get("status", {}).get("conditions", []) or []

    def container_ids(self) -> list[tuple[str, str, str]]:
        """All containers as (name, runtime, container_id).

        The reference uses only ContainerStatuses[0] and assumes the
        "docker://" prefix (pkg/util/util.go:22-23); we handle every
        container and both docker:// and containerd:// prefixes
        (SURVEY.md §7 "fix the warts").
        """
        out = []
        for cs in self.container_statuses:
            cid = cs.get("containerID", "")
            if "://" in cid:
                runtime, _, raw = cid.partition("://")
            else:
                runtime, raw = "", cid
            if raw:
                out.append((cs.get("name", ""), runtime, raw))
        return out

    def unschedulable_reason(self) -> str | None:
        """Reason string if the pod is Pending-Unschedulable.

        Reference: checkCreateState detects PodReasonUnschedulable to map to
        InsufficientGPU (allocator.go:246-281).
        """
        if self.phase != "Pending":
            return None
        for cond in self.conditions:
            if cond.get("type") == "PodScheduled" and cond.get("status") == "False":
                if cond.get("reason") == "Unschedulable":
                    return cond.get("message") or "Unschedulable"
        return None

    @property
    def qos_class(self) -> str:
        return self.obj.get("status", {}).get("qosClass", "")

    def resource_limit(self, resource: str) -> int:
        """Sum of a named resource limit across containers."""
        total = 0
        for c in self.containers:
            limits = (c.get("resources") or {}).get("limits") or {}
            val = limits.get(resource)
            if val is not None:
                total += int(str(val))
        return total

    def __repr__(self) -> str:
        return f"Pod({self.namespace}/{self.name} phase={self.phase!r} node={self.node_name!r})"


def match_label_selector(labels: dict[str, str], selector: str) -> bool:
    """Equality-based selector matching: "k=v,k2=v2" (subset used by us)."""
    if not selector:
        return True
    for clause in selector.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if "!=" in clause:
            k, _, v = clause.partition("!=")
            if labels.get(k.strip()) == v.strip():
                return False
        elif "==" in clause:
            k, _, v = clause.partition("==")
            if labels.get(k.strip()) != v.strip():
                return False
        elif "=" in clause:
            k, _, v = clause.partition("=")
            if labels.get(k.strip()) != v.strip():
                return False
        else:  # bare key: existence
            if clause not in labels:
                return False
    return True


def get_nested(obj: dict, *path: str, default: Any = None) -> Any:
    cur: Any = obj
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return default
        cur = cur[key]
    return cur
