"""In-memory fake Kubernetes client for tests.

The reference has no test substrate at all (SURVEY.md §4: all tests need a
live cluster). This fake implements the KubeClient surface with watch streams
and a pluggable scheduler hook, so the allocator / worker / master stacks are
testable in-process — including contended-scheduling scenarios (BASELINE
config 4).
"""

from __future__ import annotations

import copy
import heapq
import itertools
import threading
import time
import uuid as uuidlib
from collections.abc import Callable, Iterator

from gpumounter_tpu.k8s.client import (
    ConflictError,
    GoneError,
    KubeClient,
    NotFoundError,
    inject_write_fault,
)
from gpumounter_tpu.k8s.types import Pod, match_label_selector
from gpumounter_tpu.utils.locks import OrderedCondition
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("k8s.fake")

#: events trimmed out of the watch backlog while at least one open
#: watcher had not consumed them yet — each eviction is a future 410
#: for that watcher. A rising rate means the backlog is undersized for
#: the churn (TPUMOUNTER_WATCH_BACKLOG, docs/RUNBOOK.md 10k-nodes).
WATCH_BACKLOG_EVICTIONS = REGISTRY.counter(
    "tpumounter_watch_backlog_evictions_total",
    "watch events evicted past an open watcher's resume cursor")

SchedulerHook = Callable[[dict], None]
"""Called (with the stored pod dict, mutable) right after create_pod.
Tests use it to emulate the scheduler: set spec.nodeName, status.phase, or an
Unschedulable condition. Runs on a helper thread to mimic async scheduling."""


def _match_field_selector(pod: dict, selector: str) -> bool:
    if not selector:
        return True
    p = Pod(pod)
    for clause in selector.split(","):
        k, _, v = clause.partition("=")
        k = k.strip()
        v = v.strip()
        if k == "metadata.name" and p.name != v:
            return False
        if k == "metadata.namespace" and p.namespace != v:
            return False
        if k == "spec.nodeName" and p.node_name != v:
            return False
        if k == "status.phase" and p.phase != v:
            return False
    return True


def _merge_patch(target: dict, patch: dict) -> None:
    """RFC 7386 merge-patch, matching the API server's PATCH semantics
    for application/merge-patch+json: None deletes, dicts recurse,
    everything else replaces."""
    for key, value in patch.items():
        if value is None:
            target.pop(key, None)
        elif isinstance(value, dict) and isinstance(target.get(key), dict):
            _merge_patch(target[key], value)
        else:
            target[key] = value


class FakeKubeClient(KubeClient):
    def __init__(self, scheduler_hook: SchedulerHook | None = None,
                 scheduler_delay_s: float = 0.0,
                 delete_hook: SchedulerHook | None = None,
                 cfg=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        #: watch backlog bound, from TPUMOUNTER_WATCH_BACKLOG — 8192
        #: overruns under 10k-node churn (big-fleet benches raise it).
        self._max_events = max(64, int(cfg.watch_backlog_events))
        self._pods: dict[tuple[str, str], dict] = {}
        self._nodes: dict[str, dict] = {}
        #: API-partition simulation (recovery/chaos tests): while set,
        #: affected calls raise PartitionError (typed 503) — what a
        #: partitioned master sees from the API server. The mode makes
        #: the partition asymmetric: "full" fails everything, "reads"
        #: fails only get/list/watch, "writes" only create/delete/
        #: patch/update — the half-broken LB / one-way firewall shapes
        #: a real outage takes.
        self._partitioned = False
        self._partition_mode = "full"
        self._leases: dict[tuple[str, str], dict] = {}
        self._lease_rv = itertools.count(1)
        self._lock = OrderedCondition("k8s.fake.state")
        self._events: list[tuple[int, str, dict]] = []  # (seq, type, pod)
        self._seq = itertools.count(1)
        self.scheduler_hook = scheduler_hook
        self.delete_hook = delete_hook
        self.scheduler_delay_s = scheduler_delay_s
        self.create_calls = 0
        self.delete_calls = 0
        self.list_calls = 0
        #: last event seq emitted — the collection resourceVersion a
        #: LIST reports (list_pods_with_rv) and watchers resume from.
        self._last_seq = 0
        #: open watcher id -> last consumed seq, for the backlog
        #: eviction counter (an eviction only counts when it strands a
        #: LIVE watcher — trimming history nobody needs is free).
        self._watch_cursors: dict[int, int] = {}
        self._watch_ids = itertools.count(1)
        self.events_posted: list[tuple[str, dict]] = []
        # Single-worker async scheduler: created pods enqueue a due-time
        # into this heap and ONE thread drains it (created lazily,
        # retires when idle). The previous shape spawned a daemon thread
        # per pod — a 64-pod warm-pool refill meant 64 threads churning
        # in every test process.
        self._sched_cv = OrderedCondition("k8s.fake.sched")
        self._sched_q: list[tuple[float, int, str, str]] = []
        self._sched_seq = itertools.count(1)
        self._sched_thread: threading.Thread | None = None

    # --- event plumbing ---

    #: bounded event backlog (default; the instance bound comes from
    #: cfg.watch_backlog_events). Sequence numbers are consecutive, so
    #: any watcher can locate its resume point by arithmetic (O(1), not
    #: an O(total-events) rescan per wake — the old shape made a
    #: 1k-node churn test quadratic). A watcher that falls behind the
    #: trim horizon has its stream end, exactly like a real apiserver's
    #: 410 Gone on an expired resourceVersion: callers re-LIST and
    #: re-open (WorkerRegistry's loop and wait_for_pod already do).
    _MAX_EVENTS = 8192

    def _emit(self, etype: str, pod: dict) -> None:
        with self._lock:
            # One deepcopy per event, at emit: the stored payload is
            # immutable from then on, so watchers can filter (and copy
            # matches) outside the lock.
            seq = next(self._seq)
            self._last_seq = seq
            # Stamp the object's resourceVersion like the API server:
            # informers resume from the last event's version.
            pod.setdefault("metadata", {})["resourceVersion"] = str(seq)
            self._events.append((seq, etype, copy.deepcopy(pod)))
            overflow = len(self._events) - self._max_events
            if overflow > 0:
                # Count evictions only past the SLOWEST open watcher:
                # those events are a guaranteed future 410 for it.
                horizon = self._events[overflow - 1][0]
                evicted = 0
                for cursor in self._watch_cursors.values():
                    evicted = max(evicted,
                                  min(overflow, horizon - cursor))
                if evicted > 0:
                    WATCH_BACKLOG_EVICTIONS.inc(evicted)
                del self._events[:overflow]
            self._lock.notify_all()

    # --- KubeClient surface ---

    def _check_partition(self, kind: str = "read") -> None:
        if not self._partitioned:
            return
        mode = self._partition_mode
        if mode == "full" or (mode == "reads" and kind == "read") \
                or (mode == "writes" and kind == "write"):
            from gpumounter_tpu.k8s.client import PartitionError
            raise PartitionError(
                f"fake apiserver partitioned (set_partitioned, "
                f"mode={mode}, op={kind})")

    def set_partitioned(self, partitioned: bool,
                        mode: str = "full") -> None:
        """Simulate a network partition between this client's holder and
        the API server: affected calls fail with a typed PartitionError
        until cleared. The recovery chaos scenarios use it to model a
        stale master that can still reach workers but not the cluster
        state; mode="reads"/"writes" makes the break asymmetric
        (reads fail while writes succeed, or vice versa)."""
        if mode not in ("full", "reads", "writes"):
            raise ValueError(f"unknown partition mode {mode!r}")
        self._partitioned = bool(partitioned)
        self._partition_mode = mode

    def get_pod(self, namespace: str, name: str) -> dict:
        self._check_partition("read")
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            return copy.deepcopy(pod)

    def create_pod(self, namespace: str, manifest: dict) -> dict:
        self._check_partition("write")
        # Same injection surface as the REST client, so chaos schedules
        # hit the fake API server exactly like a real one.
        inject_write_fault("create_pod", namespace,
                           manifest.get("metadata", {}).get("name", ""))
        pod = copy.deepcopy(manifest)
        meta = pod.setdefault("metadata", {})
        meta.setdefault("namespace", namespace)
        name = meta.get("name")
        if not name:
            raise ValueError("pod manifest missing metadata.name")
        meta.setdefault("uid", str(uuidlib.uuid4()))
        pod.setdefault("status", {}).setdefault("phase", "Pending")
        with self._lock:
            if (namespace, name) in self._pods:
                raise ConflictError(f"pod {namespace}/{name} already exists")
            self._pods[(namespace, name)] = pod
            self.create_calls += 1
        self._emit("ADDED", pod)
        if self.scheduler_hook is not None:
            self._enqueue_schedule(namespace, name)
        # Copy under the store lock: with a zero scheduler delay the
        # hook thread can be mutating this very dict already, and an
        # unlocked deepcopy races it ("dictionary changed size during
        # iteration" — seen as a tier-1 flake).
        with self._lock:
            return copy.deepcopy(pod)

    # --- the single-worker async scheduler ---

    def _enqueue_schedule(self, namespace: str, name: str) -> None:
        due = time.monotonic() + self.scheduler_delay_s
        with self._sched_cv:
            heapq.heappush(self._sched_q,
                           (due, next(self._sched_seq), namespace, name))
            if self._sched_thread is None:
                self._sched_thread = threading.Thread(
                    target=self._sched_loop, name="fake-scheduler",
                    daemon=True)
                self._sched_thread.start()
            self._sched_cv.notify()

    def _sched_loop(self) -> None:
        """Drain the due-time heap. Concurrent creates still schedule
        concurrently — their due times all start the same delay apart
        from now, and the heap fires each when due — but on one thread.
        Retires after a short idle linger; the next create restarts it."""
        while True:
            with self._sched_cv:
                if not self._sched_q:
                    self._sched_cv.wait(timeout=0.05)
                    if not self._sched_q:
                        self._sched_thread = None
                        return
                due, _, namespace, name = self._sched_q[0]
                now = time.monotonic()
                if due > now:
                    self._sched_cv.wait(timeout=due - now)
                    continue
                heapq.heappop(self._sched_q)
            # Mutate the stored pod under the store lock: concurrent
            # get/list/watch deepcopy the store and must never observe
            # a half-written status. (Condition() wraps an RLock, so
            # _emit's re-acquisition inside is fine.)
            try:
                with self._lock:
                    stored = self._pods.get((namespace, name))
                    if stored is None:
                        continue
                    self.scheduler_hook(stored)
                    self._emit("MODIFIED", stored)
            except Exception:  # noqa: BLE001 — a bad hook must not
                # take the shared scheduler down with it
                logger.exception("scheduler hook failed for %s/%s",
                                 namespace, name)

    def delete_pod(self, namespace: str, name: str, grace_period_seconds: int = 0) -> None:
        self._check_partition("write")
        try:
            inject_write_fault("delete_pod", namespace, name)
        except NotFoundError:
            return  # match the REST client: delete-of-missing is a no-op
        with self._lock:
            pod = self._pods.pop((namespace, name), None)
            self.delete_calls += 1
        if pod is not None:
            if self.delete_hook is not None:
                self.delete_hook(pod)
            self._emit("DELETED", pod)

    def list_pods(self, namespace: str | None = None, label_selector: str = "",
                  field_selector: str = "") -> list[dict]:
        return self.list_pods_with_rv(namespace,
                                      label_selector=label_selector,
                                      field_selector=field_selector)[0]

    def list_pods_with_rv(self, namespace: str | None = None,
                          label_selector: str = "",
                          field_selector: str = "",
                          ) -> tuple[list[dict], str]:
        self._check_partition("read")
        # Filter FIRST, deepcopy only the matches: a selector LIST over
        # a 1k-pod cluster used to deepcopy every pod (the fake's
        # dominant cost at fleet scale — the registry, the reconciler
        # resync and the warm-pool resync all LIST with selectors).
        with self._lock:
            self.list_calls += 1
            out = []
            for (ns, _name), pod in self._pods.items():
                if namespace and ns != namespace:
                    continue
                p = Pod(pod)
                if not match_label_selector(p.labels, label_selector):
                    continue
                if not _match_field_selector(pod, field_selector):
                    continue
                out.append(copy.deepcopy(pod))
            rv = str(self._last_seq)
        return out, rv

    def watch_pods(self, namespace: str, *, label_selector: str = "",
                   field_selector: str = "", timeout_s: float = 60.0,
                   resource_version: str = "") -> Iterator[tuple[str, dict]]:
        self._check_partition("read")
        # Subscribe EAGERLY (cursor captured at call time, not at first
        # next()): callers rely on open-watch-then-recheck to close the
        # missed-event window (KubeClient.wait_for_pod).
        deadline = time.monotonic() + timeout_s
        with self._lock:
            if resource_version:
                # Resume AFTER the given version (informer protocol).
                # A cursor that already fell behind the trim horizon is
                # the real apiserver's immediate 410 on watch open.
                try:
                    cursor = int(resource_version)
                except ValueError:
                    cursor = self._events[-1][0] if self._events else 0
                else:
                    if self._events and cursor < self._events[0][0] - 1:
                        raise GoneError(
                            f"resourceVersion {resource_version} is too "
                            f"old (backlog starts at "
                            f"{self._events[0][0]})")
            else:
                cursor = self._events[-1][0] if self._events else 0
        return self._watch_iter(namespace, label_selector, field_selector,
                                deadline, cursor)

    def _pending_locked(self, cursor: int) -> list | None:
        """Events after `cursor` (a slice copy — safe to read unlocked:
        payloads are immutable after emit). None = the backlog was
        trimmed past this watcher (the fake's 410 Gone: the stream must
        end so the caller re-LISTs and re-opens). Caller holds _lock."""
        if not self._events:
            return []
        first = self._events[0][0]
        if cursor < first - 1:
            return None
        start = cursor - (first - 1)  # seqs are consecutive: O(1) resume
        return self._events[start:]

    def _watch_iter(self, namespace, label_selector, field_selector,
                    deadline, cursor) -> Iterator[tuple[str, dict]]:
        watch_id = next(self._watch_ids)
        try:
            while True:
                with self._lock:
                    self._watch_cursors[watch_id] = cursor
                    pending = self._pending_locked(cursor)
                    if pending is not None and not pending:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return
                        self._lock.wait(timeout=min(remaining, 0.25))
                        pending = self._pending_locked(cursor)
                if pending is None:
                    logger.warning("watch backlog trimmed past cursor %d; "
                                   "ending stream (caller must re-list)",
                                   cursor)
                    return
                # Filter + deepcopy OUTSIDE the lock: event payloads are
                # immutable after emit, and only matches pay the copy — a
                # field-selector watch (one pod) over heavy churn was
                # paying a deepcopy per event per watcher.
                for seq, etype, pod in pending:
                    cursor = max(cursor, seq)
                    p = Pod(pod)
                    if namespace and p.namespace != namespace:
                        continue
                    if not match_label_selector(p.labels, label_selector):
                        continue
                    if not _match_field_selector(pod, field_selector):
                        continue
                    yield etype, copy.deepcopy(pod)
                if time.monotonic() >= deadline:
                    return
        finally:
            with self._lock:
                self._watch_cursors.pop(watch_id, None)

    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        self._check_partition("write")
        inject_write_fault("patch_pod", namespace, name)
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            _merge_patch(pod, copy.deepcopy(patch))
            stored = copy.deepcopy(pod)
        self._emit("MODIFIED", stored)
        return stored

    def create_event(self, namespace: str, manifest: dict) -> dict:
        with self._lock:
            self.events_posted.append((namespace, copy.deepcopy(manifest)))
        return manifest

    # --- leases (coordination.k8s.io/v1 fake; shard leader election) ---
    #
    # Same CAS semantics as the API server: every stored lease carries a
    # monotonically-increasing resourceVersion, and update_lease rejects
    # a manifest whose resourceVersion is not the current one — the
    # property the shard manager's single-owner invariant rests on.

    def get_lease(self, namespace: str, name: str) -> dict:
        self._check_partition("read")
        with self._lock:
            lease = self._leases.get((namespace, name))
            if lease is None:
                raise NotFoundError(f"lease {namespace}/{name} not found")
            return copy.deepcopy(lease)

    def create_lease(self, namespace: str, manifest: dict) -> dict:
        self._check_partition("write")
        inject_write_fault("create_lease", namespace,
                           manifest.get("metadata", {}).get("name", ""))
        lease = copy.deepcopy(manifest)
        meta = lease.setdefault("metadata", {})
        meta.setdefault("namespace", namespace)
        name = meta.get("name")
        if not name:
            raise ValueError("lease manifest missing metadata.name")
        with self._lock:
            if (namespace, name) in self._leases:
                raise ConflictError(
                    f"lease {namespace}/{name} already exists")
            meta["resourceVersion"] = str(next(self._lease_rv))
            self._leases[(namespace, name)] = lease
            return copy.deepcopy(lease)

    def update_lease(self, namespace: str, name: str,
                     manifest: dict) -> dict:
        self._check_partition("write")
        inject_write_fault("update_lease", namespace, name)
        with self._lock:
            current = self._leases.get((namespace, name))
            if current is None:
                raise NotFoundError(f"lease {namespace}/{name} not found")
            sent_rv = manifest.get("metadata", {}).get("resourceVersion")
            have_rv = current.get("metadata", {}).get("resourceVersion")
            if sent_rv != have_rv:
                raise ConflictError(
                    f"lease {namespace}/{name}: resourceVersion conflict "
                    f"(sent {sent_rv}, have {have_rv})")
            lease = copy.deepcopy(manifest)
            lease.setdefault("metadata", {})["resourceVersion"] = \
                str(next(self._lease_rv))
            lease["metadata"].setdefault("namespace", namespace)
            lease["metadata"].setdefault("name", name)
            self._leases[(namespace, name)] = lease
            return copy.deepcopy(lease)

    # --- core/v1 Nodes (recovery plane) ---

    def get_node(self, name: str) -> dict:
        self._check_partition("read")
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise NotFoundError(f"node {name} not found")
            return copy.deepcopy(node)

    def list_nodes(self) -> list[dict]:
        self._check_partition("read")
        with self._lock:
            return [copy.deepcopy(n) for n in self._nodes.values()]

    def create_node(self, name: str, ready: bool = True) -> dict:
        """Test helper: register a Node object with a Ready condition."""
        node = {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": name, "uid": str(uuidlib.uuid4())},
            "status": {"conditions": [{
                "type": "Ready",
                "status": "True" if ready else "False",
            }]},
        }
        with self._lock:
            self._nodes[name] = node
            return copy.deepcopy(node)

    def set_node_ready(self, name: str, ready: bool,
                       reason: str = "") -> None:
        """Kill/partition simulation: flip the node's Ready condition —
        what the kubelet stopping its heartbeats looks like from the
        API server."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise NotFoundError(f"node {name} not found")
            node["status"]["conditions"] = [{
                "type": "Ready",
                "status": "True" if ready else "False",
                **({"reason": reason} if reason else {}),
            }]

    def delete_node(self, name: str) -> None:
        with self._lock:
            self._nodes.pop(name, None)

    # --- test helpers ---

    def set_pod_status(self, namespace: str, name: str, **status) -> None:
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            pod.setdefault("status", {}).update(status)
            stored = copy.deepcopy(pod)
        self._emit("MODIFIED", stored)

    def mark_unschedulable(self, namespace: str, name: str,
                           message: str = "0/1 nodes have free TPU") -> None:
        """Emulates the scheduler's Unschedulable condition.

        Reference detects this via PodReasonUnschedulable in checkCreateState
        (allocator.go:262-270).
        """
        self.set_pod_status(namespace, name, phase="Pending", conditions=[{
            "type": "PodScheduled", "status": "False",
            "reason": "Unschedulable", "message": message,
        }])

    def mark_running(self, namespace: str, name: str, node: str = "",
                     pod_ip: str = "") -> None:
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            if node:
                pod.setdefault("spec", {})["nodeName"] = node
            status = pod.setdefault("status", {})
            status["phase"] = "Running"
            if pod_ip:
                status["podIP"] = pod_ip
            stored = copy.deepcopy(pod)
        self._emit("MODIFIED", stored)
