from gpumounter_tpu.k8s.client import (
    ApiError,
    ConflictError,
    KubeClient,
    NotFoundError,
    RestKubeClient,
    in_cluster_client,
)
from gpumounter_tpu.k8s.types import Pod

__all__ = [
    "ApiError",
    "ConflictError",
    "KubeClient",
    "NotFoundError",
    "Pod",
    "RestKubeClient",
    "in_cluster_client",
]
