from gpumounter_tpu.k8s.client import (
    ApiError,
    ApiTimeoutError,
    ConflictError,
    KubeClient,
    NotFoundError,
    PartitionError,
    RestKubeClient,
    ServerError,
    default_client,
    in_cluster_client,
    kubeconfig_client,
)
from gpumounter_tpu.k8s.types import Pod

__all__ = [
    "ApiError",
    "ApiTimeoutError",
    "ConflictError",
    "KubeClient",
    "NotFoundError",
    "PartitionError",
    "Pod",
    "RestKubeClient",
    "ServerError",
    "default_client",
    "in_cluster_client",
    "kubeconfig_client",
]
