from gpumounter_tpu.k8s.client import (
    ApiError,
    ConflictError,
    KubeClient,
    NotFoundError,
    RestKubeClient,
    default_client,
    in_cluster_client,
    kubeconfig_client,
)
from gpumounter_tpu.k8s.types import Pod

__all__ = [
    "ApiError",
    "ConflictError",
    "KubeClient",
    "NotFoundError",
    "Pod",
    "RestKubeClient",
    "default_client",
    "in_cluster_client",
    "kubeconfig_client",
]
