"""Ring attention: sequence/context parallelism over a named mesh axis.

Long-context support for workloads running on hot-mounted chip sets
(SURVEY.md §2b: the reference has no compute stack at all; our tenant-side
obligation is that the chips we mount are *usable* for modern workloads,
and long sequences are the canonical reason to hot-add chips mid-job).

TPU-first design: the sequence axis is sharded over a mesh axis; each
device holds a Q/K/V chunk and K/V chunks rotate around the ring with
`jax.lax.ppermute` — XLA lowers this to neighbor-to-neighbor ICI transfers
that overlap with the per-chunk attention compute. Softmax is combined
online (flash-attention style running max/denominator), so memory stays
O(chunk²) instead of O(seq²) and no device ever materializes the full
attention matrix.

No NCCL/MPI analog anywhere: the collective IS the jax primitive
(scaling-book recipe: mesh + shardings + XLA collectives).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _chunk_attention(q, k, v, q_pos, k_pos, m_prev, num_prev, den_prev,
                     scale, causal):
    """One ring step of online-softmax attention.

    q: (B, H, Lq, D); k/v: (B, H, Lk, D); positions are global indices for
    causal masking. Accumulators: m (B,H,Lq,1), num (B,H,Lq,D),
    den (B,H,Lq,1) — combined across steps in fp32.
    """
    if k.shape[1] != q.shape[1]:
        # GQA: broadcast INSIDE the chunk step so the ring rotates the
        # compact H_kv heads (ICI volume and per-device K/V memory stay
        # H_kv/H of the broadcast size); only this transient score
        # computation sees full heads.
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
        scores = jnp.where(mask, scores, -jnp.inf)
    m_chunk = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_chunk)
    # Fully-masked rows produce -inf maxima; keep exp() finite.
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - m_safe)
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    correction = jnp.exp(jnp.where(jnp.isneginf(m_prev), -jnp.inf,
                                   m_prev - m_safe))
    correction = jnp.where(jnp.isneginf(m_prev), 0.0, correction)
    num_new = num_prev * correction + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    den_new = den_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    return m_new, num_new, den_new


def _ring_attention_local(q, k, v, *, axis_name: str, scale: float,
                          causal: bool):
    """Per-device body (runs under shard_map). Shapes are local chunks:
    q/k/v (B, H, L_local, D); returns (B, H, L_local, D)."""
    n_dev = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    chunk = q.shape[2]
    q_pos = my_idx * chunk + jnp.arange(chunk)

    b, h, lq, d = q.shape
    m0 = jnp.full((b, h, lq, 1), -jnp.inf, jnp.float32)
    num0 = jnp.zeros((b, h, lq, d), jnp.float32)
    den0 = jnp.zeros((b, h, lq, 1), jnp.float32)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(carry, s):
        k_cur, v_cur, m, num, den = carry
        # K/V chunk currently held originated on device (my_idx - s) mod n.
        src = (my_idx - s) % n_dev
        k_pos = src * chunk + jnp.arange(chunk)
        m, num, den = _chunk_attention(q, k_cur, v_cur, q_pos, k_pos,
                                       m, num, den, scale, causal)
        # Rotate K/V to the next device; overlaps with next-step compute
        # after XLA schedules the ICI DMA.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, num, den), None

    (k, v, m, num, den), _ = jax.lax.scan(
        step, (k, v, m0, num0, den0), jnp.arange(n_dev))
    out = num / jnp.maximum(den, 1e-30)
    return out.astype(q.dtype)


def _combine_chunks(o_prev, lse_prev, o_chunk, lse_chunk):
    """Merge two normalized partial-attention results via their
    log-sum-exps: o = Σᵢ oᵢ·exp(lseᵢ − logaddexp(lse₁, lse₂))."""
    lse_new = jnp.logaddexp(lse_prev, lse_chunk)
    w_prev = jnp.exp(lse_prev - lse_new)[..., None]
    w_chunk = jnp.exp(lse_chunk - lse_new)[..., None]
    return o_prev * w_prev + o_chunk * w_chunk, lse_new


def _ring_flash_local(q, k, v, *, axis_name: str, scale: float,
                      causal: bool, block_q: int, block_k: int,
                      interpret: bool, softcap: float | None = None):
    """Per-device ring body with the Pallas flash kernel as the inner
    chunk step. Memory is O(chunk·D) — no (Lq, Lk) score matrix even per
    chunk — and causal chunk classification is real control flow
    (lax.cond), so fully-future chunks cost nothing on the MXU:

      src >  my_idx → every key is in the future: skip entirely
      src == my_idx → the diagonal chunk: causal flash
      src <  my_idx → whole chunk in the past: non-causal flash

    Cross-chunk combination uses the kernel's lse output
    (flash-decoding combine), all in fp32.
    """
    from gpumounter_tpu.ops.flash_attention import (
        NEG_INF, flash_attention_with_lse)

    n_dev = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    o0 = jnp.zeros((b, h, lq, d), jnp.float32)
    # Must match the kernel's masked-row sentinel exactly: the combine
    # weights a fully-masked chunk exp(NEG_INF - x) == 0 only if both
    # sides use the same NEG_INF.
    lse0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def attend(q_, k_, v_, causal_):
        # custom-VJP wrapper: trainable, lse cotangent folded into Δ.
        # softcap composes with the cross-chunk combine exactly: capping
        # is per-score, and the lse of capped scores merges like any lse.
        return flash_attention_with_lse(q_, k_, v_, causal_, scale,
                                        block_q, block_k, interpret,
                                        None, softcap)

    def step(carry, s):
        k_cur, v_cur, o, lse = carry
        src = (my_idx - s) % n_dev

        def diag(args):
            o, lse = args
            oc, lsec = attend(q, k_cur, v_cur, True)
            return _combine_chunks(o, lse, oc.astype(jnp.float32), lsec)

        def past(args):
            o, lse = args
            oc, lsec = attend(q, k_cur, v_cur, False)
            return _combine_chunks(o, lse, oc.astype(jnp.float32), lsec)

        if causal:
            o, lse = jax.lax.cond(
                src > my_idx, lambda args: args,
                lambda args: jax.lax.cond(src == my_idx, diag, past, args),
                (o, lse))
        else:
            o, lse = past((o, lse))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o, lse), None

    (k, v, o, lse), _ = jax.lax.scan(
        step, (k, v, o0, lse0), jnp.arange(n_dev))
    return o.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   *, seq_axis: str = "seq", causal: bool = True,
                   scale: float | None = None, impl: str = "auto",
                   block_q: int = 256, block_k: int = 512,
                   softcap: float | None = None,
                   data_axis: str | None = None) -> jax.Array:
    """Sequence-parallel attention over `mesh`'s `seq_axis`.

    q, k, v: (batch, heads, seq, head_dim), sharded (or shardable) with
    the sequence dimension split over `seq_axis`. Returns same shape/
    sharding. Use inside jit; XLA emits ppermute ICI transfers.

    data_axis: name of a mesh axis the BATCH dim is sharded over (the
    dp x sp training step). Without it, batch-sharded operands entering
    the shard_map would be gathered; the ring itself still runs only
    over seq_axis — batch shards are independent.

    impl: "flash" runs the Pallas flash kernel per ring chunk (lse-based
    cross-chunk combine, O(chunk·D) memory, causal chunks skipped by
    lax.cond — interpret mode off-TPU so it works everywhere); "xla"
    keeps the einsum online-softmax body (materializes per-chunk scores,
    shape-robust); "auto" picks flash on TPU inside the measured
    envelope (causal, head_dim 128, lane-aligned chunks) and xla
    otherwise.

    softcap: Gemma-2-style logit capping cap·tanh(s/cap), applied per
    chunk score (it composes exactly with the lse combine). Only the
    flash body caps, so softcap forces impl="flash": auto takes the
    flash body even off-TPU (interpret mode), impl="xla" raises, and on
    TPU an un-tileable chunk raises a clear error instead of failing in
    Mosaic.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if q.shape[1] % k.shape[1]:
        # Same explicit check as the ops-level paths — fail here with a
        # clear message, not deep inside shard_map with a shape error.
        raise ValueError(f"q heads ({q.shape[1]}) must be a multiple of "
                         f"kv heads ({k.shape[1]})")
    spec = P(data_axis, None, seq_axis, None)
    on_tpu = any(dev.platform == "tpu" for dev in mesh.devices.flat)
    # Per-device chunk geometry, shared by auto dispatch and the
    # forced-flash guard (ONE source of truth for the alignment rule).
    from gpumounter_tpu.ops.flash_attention import (
        _MEASURED_HEAD_DIM, _fit_block)
    chunk = q.shape[2] // mesh.shape[seq_axis]
    bq, bk = _fit_block(chunk, block_q), _fit_block(chunk, block_k)
    blocks_ok = bq % 128 == 0 and bk % 128 == 0

    def _refuse_unaligned(why: str):
        raise ValueError(
            f"ring_attention: {why} needs the flash body but the "
            f"per-device chunk ({chunk}) does not tile into "
            f"lane-aligned blocks (fit: {bq}x{bk}); pad the sequence "
            f"so chunks are multiples of 128")

    if impl == "auto":
        # Same envelope discipline as ops-level auto dispatch: only take
        # the Pallas body when the per-device chunk yields lane-aligned
        # blocks and head_dim is the measured 128 — Mosaic compiles
        # unaligned tiles poorly or not at all, and the previously
        # always-XLA body handled those shapes fine.
        in_envelope = (causal and q.shape[-1] == _MEASURED_HEAD_DIM
                       and blocks_ok)
        if softcap is not None:
            # Only the flash body caps logits; interpret mode covers
            # non-TPU platforms. On TPU an out-of-envelope shape would
            # hand Mosaic unaligned tiles — refuse loudly rather than
            # fail deep in the compiler.
            if on_tpu and not blocks_ok:
                _refuse_unaligned("softcap")
            impl = "flash"
        else:
            impl = "flash" if (on_tpu and in_envelope) else "xla"
    if impl == "flash":
        if on_tpu and not blocks_ok:
            # Forced flash gets the SAME actionable refusal as auto
            # dispatch (ADVICE r3): an unaligned per-device chunk would
            # otherwise fail deep inside Mosaic with an opaque error.
            _refuse_unaligned("impl='flash'")
        body = partial(_ring_flash_local, axis_name=seq_axis, scale=scale,
                       causal=causal, block_q=block_q, block_k=block_k,
                       interpret=not on_tpu, softcap=softcap)
    elif impl == "xla":
        if softcap is not None:
            raise ValueError("softcap requires impl='flash' (the einsum "
                             "body does not cap logits)")
        body = partial(_ring_attention_local, axis_name=seq_axis,
                       scale=scale, causal=causal)
    else:
        raise ValueError(f"unknown impl {impl!r}")
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True,
                        scale: float | None = None) -> jax.Array:
    """Single-device O(L²) attention; the correctness oracle for tests."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        l_q, l_k = q.shape[2], k.shape[2]
        mask = jnp.arange(l_k)[None, :] <= jnp.arange(l_q)[:, None]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def shard_qkv(x: jax.Array, mesh: Mesh, seq_axis: str = "seq") -> jax.Array:
    """Place a (B, H, L, D) array with L split over the mesh's seq axis."""
    return jax.device_put(
        x, NamedSharding(mesh, P(None, None, seq_axis, None)))
