"""Device-mesh construction over (possibly hot-mounted) chip sets.

TPU-first: scaling is expressed as a `jax.sharding.Mesh` with named axes and
NamedSharding annotations — XLA inserts the collectives and rides ICI
(SURVEY.md §5 "distributed communication backend": we expose the fabric to
JAX rather than writing a comm library). After a hot-mount changes the chip
set, tenants rebuild the mesh with `build_mesh(jax.devices())`.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def mesh_shape_for(n_devices: int) -> tuple[int, int]:
    """(data, model) mesh shape: widest model axis that divides n_devices,
    capped at 8 (a v5e host), model axis preferred over ICI-local groups."""
    model = 1
    for cand in (8, 4, 2):
        if n_devices % cand == 0 and n_devices >= cand:
            model = cand
            break
    return n_devices // model, model


def build_mesh(devices=None, axis_names: tuple[str, str] = ("data", "model")) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    data, model = mesh_shape_for(len(devices))
    import numpy as np
    arr = np.array(devices).reshape(data, model)
    return Mesh(arr, axis_names)
