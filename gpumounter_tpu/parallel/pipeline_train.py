"""Pipeline-parallel (pp) training of the flagship probe.

Microbatch-pipelined training over the probe's transformer blocks: a
1-axis ("pipe",) mesh of P devices, each owning its share of the block
stack (stage-stacked parameters sharded over the axis); activations
move stage-to-stage on ppermute inside parallel/pipeline's schedule,
and the whole thing differentiates — the tick loop has static bounds —
so one jitted step does forward, backward, and the SGD update.

Two schedules (parallel/pipeline.py): GPipe (`n_virtual=1`, each device
one contiguous block chunk) and interleaved/circular (`n_virtual=v`,
each device v non-contiguous chunks — logical stage k·P + d on device
d — cutting the bubble fraction by ~v, the Megatron "interleaved 1F1B"
family). Bubble accounting is enforced: n_micro >= n_stages, and
schedule_info() exposes the tick/bubble arithmetic for callers.

Embedding and the logits matmul live OUTSIDE the pipeline (they are
token-local and tied to one table; only the block stack is staged).
Inside a stage the blocks run exactly models/probe._block with
mesh=None — which means the flash-attention kernel dispatches per the
committed train table INSIDE the pipeline's shard_map, the same
kernel-under-shard_map recipe as the dp x tp layout.

Reference note: GPUMounter has no compute stack at all (SURVEY.md §2b);
this completes the flagship's parallelism inventory — dp, tp, sp
(ring), ep (MoE), and pp now all drive the same probe model.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gpumounter_tpu.models.probe import (
    TransformerConfig, _block, next_token_nll)
from gpumounter_tpu.parallel.pipeline import (
    pipeline_apply, schedule_info, shard_stage_params)
from gpumounter_tpu.parallel.train_step import sgd_update


def to_pipeline_params(params: dict, n_stages: int,
                       n_virtual: int = 1) -> dict:
    """Regroup init_params() output for a pipeline of P = n_stages
    devices and v = n_virtual chunks per device.

    The block list becomes stage-stacked leaves: (P, L/P, ...) for
    GPipe, (P, v, L/(P·v), ...) interleaved — logical stage s = k·P + d
    (device d, chunk k) owns blocks [s·per, (s+1)·per). embed (and pos)
    stay as-is.
    """
    blocks = params["blocks"]
    total = n_stages * n_virtual
    if len(blocks) % total:
        raise ValueError(f"n_layers ({len(blocks)}) must divide by "
                         f"n_stages*n_virtual ({n_stages}*{n_virtual})")
    per = len(blocks) // total

    def logical_stage(s: int):
        return jax.tree.map(lambda *xs: jnp.stack(xs),
                            *blocks[s * per:(s + 1) * per])

    if n_virtual == 1:
        stages = [logical_stage(d) for d in range(n_stages)]
    else:
        # device-major, chunk-minor: leaf axes (P, v, per, ...)
        stages = [
            jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[logical_stage(k * n_stages + d)
                  for k in range(n_virtual)])
            for d in range(n_stages)
        ]
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["stages"] = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    return out


def shard_pipeline_params(params: dict, mesh: Mesh,
                          pipe_axis: str = "pipe") -> dict:
    """Stages over the pipe axis; embed/pos replicated."""
    placed = {k: jax.device_put(v, NamedSharding(mesh, P()))
              for k, v in params.items() if k != "stages"}
    placed["stages"] = shard_stage_params(params["stages"], mesh,
                                          pipe_axis)
    return placed


def make_pipeline_train_step(mesh: Mesh, cfg: TransformerConfig,
                             n_micro: int, lr: float = 1e-3,
                             pipe_axis: str = "pipe",
                             n_virtual: int = 1):
    """step(params, tokens) -> (params, loss) over a ("pipe",) mesh.

    params come from to_pipeline_params(init_params(cfg, key), P, v).
    n_virtual=v > 1 selects the interleaved/circular schedule (bubble
    fraction ~ (P-1)/(M·v+P-1) instead of GPipe's (P-1)/(M+P-1)).
    Restrictions: dense FFN only (the MoE aux loss would need
    cross-stage accumulation the schedule does not carry), and
    attn_parallel must be "heads" (each stage attends its full
    sequence locally; combine pp with sp/tp via nested meshes later).
    """
    n_stages = mesh.shape[pipe_axis]
    total = n_stages * n_virtual
    if cfg.n_layers % total:
        raise ValueError(f"n_layers ({cfg.n_layers}) must divide by "
                         f"pipeline stages*chunks ({n_stages}*{n_virtual})")
    if n_micro < n_stages:
        # Bubble accounting: with M < P the ramp never fills — at least
        # one device idles >50% of the schedule. Refuse rather than
        # silently train at a fraction of the hardware.
        info = schedule_info(n_micro, n_stages, n_virtual)
        raise ValueError(
            f"n_micro ({n_micro}) must be >= pipeline stages "
            f"({n_stages}): bubble fraction would be "
            f"{info['bubble_fraction']:.2f} "
            f"({info['bubble_ticks']}/{info['ticks']} ticks)")
    if cfg.n_experts is not None:
        raise ValueError("pipeline training supports dense FFN only "
                         "(MoE aux loss is not carried across stages)")
    if cfg.attn_parallel != "heads":
        raise ValueError("pipeline training requires "
                         "attn_parallel='heads'")
    per = cfg.n_layers // total

    def stage_fn(chunk_params, x):
        for i in range(per):
            blk = jax.tree.map(lambda a, i=i: a[i], chunk_params)
            # mesh=None: inside the pipeline's shard_map every stage is
            # a single device — the kernel dispatches directly.
            # train=True: this call is differentiated (value_and_grad in
            # step), so dispatch must pick fwd+bwd-valid geometries from
            # _TRAIN_TABLE; some fwd-only _SWEEP_TABLE winners have no
            # compiling backward grid on real TPU.
            x, _aux = _block(x, blk, cfg, train=True)
        return x

    def loss_fn(params, tokens):
        t = tokens.shape[1]
        x = params["embed"][tokens]
        if not cfg.rope:
            x = x + params["pos"][:t]
        x = pipeline_apply(params["stages"], x, mesh, stage_fn,
                           n_micro=n_micro, pipe_axis=pipe_axis,
                           n_virtual=n_virtual)
        logits = (x @ params["embed"].T).astype(jnp.float32)
        return next_token_nll(logits, tokens)

    def step(params, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        return sgd_update(params, grads, lr), loss

    # The param structure is fully determined by cfg (pos exists iff
    # not rope; one stacked block dict per stage), so the shardings —
    # and the jit — are built eagerly.
    stage_sharding = NamedSharding(mesh, P(pipe_axis))
    repl = NamedSharding(mesh, P())
    from gpumounter_tpu.models.probe import init_params
    template = jax.eval_shape(
        lambda: to_pipeline_params(
            init_params(cfg, jax.random.key(0)), n_stages, n_virtual))
    shardings = {k: (jax.tree.map(lambda _: stage_sharding, v)
                     if k == "stages" else repl)
                 for k, v in template.items()}
    return jax.jit(step, in_shardings=(shardings, repl),
                   out_shardings=(shardings, repl))
