"""Sharded training step over a named mesh (dp × tp).

Used by the multi-chip dry-run and by post-hot-mount validation: after chips
appear, the tenant rebuilds the mesh and resumes stepping with the same
functions. Shardings: batch over "data"; attention/MLP weights over "model"
(column/row split so XLA emits a single psum per block on ICI); everything
jit-compiled with explicit NamedSharding in/out specs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gpumounter_tpu.models.probe import TransformerConfig, loss_fn


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs: tensor-parallel over the "model" axis.

    wqkv/w1 column-split (output dim), wo/w2 row-split (input dim) — the
    Megatron layout; XLA inserts one reduce per block boundary.
    """
    block = {
        "wqkv": P(None, "model"),
        "wo": P("model", None),
        "w1": P(None, "model"),
        "w2": P("model", None),
        "ln1": P(None),
        "ln2": P(None),
    }
    return {
        "embed": P(None, None),
        "pos": P(None, None),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
    }


def shard_params(params: dict, mesh: Mesh, cfg: TransformerConfig) -> dict:
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
        is_leaf=lambda x: isinstance(x, jax.Array) or hasattr(x, "shape"))


def make_train_step(mesh: Mesh, cfg: TransformerConfig, lr: float = 1e-3):
    """Returns step(params, tokens) -> (params, loss), jitted over the mesh."""
    specs = param_specs(cfg)
    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                   is_leaf=lambda x: isinstance(x, P))
    data_sharding = NamedSharding(mesh, P("data", None))

    def step(params, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg))(params)
        new_params = jax.tree.map(
            lambda p, g: (p.astype(jnp.float32) - lr * g.astype(jnp.float32)
                          ).astype(p.dtype), params, grads)
        return new_params, loss

    return jax.jit(
        step,
        in_shardings=(param_shardings, data_sharding),
        out_shardings=(param_shardings, NamedSharding(mesh, P())),
    )
