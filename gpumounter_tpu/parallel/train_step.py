"""Sharded training step over a named 2-axis mesh.

Used by the multi-chip dry-run and by post-hot-mount validation: after chips
appear, the tenant rebuilds the mesh and resumes stepping with the same
functions. Two layouts, selected by TransformerConfig.attn_parallel:

  * "heads" (dp x tp, mesh axes ("data", "model")): batch over "data";
    attention/MLP weights over "model" (Megatron column/row split so
    XLA emits a single psum per block on ICI); MoE expert weights shard
    their expert dim over "model". The mesh is threaded into loss_fn,
    so attention executes the Pallas flash kernel under a shard_map
    nested inside the GSPMD step (models/probe._attention) forward AND
    backward, rather than pinning the fused XLA path.
  * "seq" (dp x sp, any axis names): long context — parameters
    replicated, TOKENS sharded over the second axis, and every block's
    attention is parallel/ring_attention (K/V chunks rotating on
    ppermute), so per-device activation memory is O(L / n_shards).

Everything jit-compiled with explicit NamedSharding in/out specs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gpumounter_tpu.models.probe import TransformerConfig, loss_fn


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs: tensor-parallel over the "model" axis.

    Dense blocks: wqkv/w1 column-split (output dim), wo/w2 row-split
    (input dim) — the Megatron layout; XLA inserts one reduce per block
    boundary. MoE blocks: the stacked expert weights shard their EXPERT
    dimension over "model" (expert parallelism riding the same
    ICI-local axis), router replicated.
    """
    block = {
        "wqkv": P(None, "model"),
        "wo": P("model", None),
        "ln1": P(None),
        "ln2": P(None),
    }
    if cfg.n_experts is None:
        block["w1"] = P(None, "model")
        block["w2"] = P("model", None)
    else:
        from gpumounter_tpu.parallel.moe import moe_param_specs
        block.update(moe_param_specs(axis="model"))
    specs = {
        "embed": P(None, None),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
    }
    if not cfg.rope:  # rope configs carry no learned position table
        specs["pos"] = P(None, None)
    if cfg.attn_parallel == "seq":
        # dp x sp: parameters fully replicated — the parallelism lives
        # in the activations (tokens over the sequence axis) and ring
        # attention's rotating K/V chunks, so the mesh's second axis
        # never partitions a weight.
        specs = jax.tree.map(lambda s: P(), specs,
                             is_leaf=lambda x: isinstance(x, P))
    return specs


def _data_spec(mesh: Mesh, cfg: TransformerConfig) -> P:
    """Sharding for the (batch, seq) token batch: batch over the first
    axis always; seq over the second axis in the dp x sp layout."""
    first, second = mesh.axis_names
    if cfg.attn_parallel == "seq":
        return P(first, second)
    return P(first, None)


def shard_params(params: dict, mesh: Mesh, cfg: TransformerConfig) -> dict:
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
        is_leaf=lambda x: isinstance(x, jax.Array) or hasattr(x, "shape"))


def sgd_update(params, grads, lr: float):
    """fp32 SGD update cast back to each param's dtype — the one
    update rule shared by every hand-rolled step (here and the
    pipeline step)."""
    return jax.tree.map(
        lambda p, g: (p.astype(jnp.float32) - lr * g.astype(jnp.float32)
                      ).astype(p.dtype), params, grads)


def make_train_step(mesh: Mesh, cfg: TransformerConfig, lr: float = 1e-3):
    """Returns step(params, tokens) -> (params, loss), jitted over the mesh."""
    specs = param_specs(cfg)
    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                   is_leaf=lambda x: isinstance(x, P))
    data_sharding = NamedSharding(mesh, _data_spec(mesh, cfg))

    def step(params, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg, mesh))(params)
        return sgd_update(params, grads, lr), loss

    return jax.jit(
        step,
        in_shardings=(param_shardings, data_sharding),
        out_shardings=(param_shardings, NamedSharding(mesh, P())),
    )


def make_train_step_optax(mesh: Mesh, cfg: TransformerConfig, tx):
    """Sharded train step driven by an optax optimizer (adamw, lion,
    schedules, chains — anything implementing GradientTransformation).

    Returns (init_fn, step_fn):
      opt_state = init_fn(params)                  # sharded by
                                                   # propagation from the
                                                   # param shardings
      params, opt_state, loss = step_fn(params, opt_state, tokens)

    Supported optimizers: transformations whose state subtrees MIRROR
    the parameter pytree (sgd/momentum, adam(w), lion, and chains of
    them) — those subtrees are placed on the tensor-parallel param
    shardings. State that does not mirror the params (optax.masked,
    multi_transform, adafactor's factored moments) cannot be placed
    automatically; rather than silently replicate large tensors onto
    every device (~mesh-size x memory), init_fn raises and asks the
    caller to place that state explicitly.
    """
    import optax

    specs = param_specs(cfg)
    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                   is_leaf=lambda x: isinstance(x, P))
    data_sharding = NamedSharding(mesh, _data_spec(mesh, cfg))
    param_treedef = jax.tree.structure(param_shardings)

    def _is_param_tree(x):
        try:
            return jax.tree.structure(x) == param_treedef
        except Exception:  # noqa: BLE001 — non-pytree leaf
            return False

    def init_fn(params):
        # jit leaves unconstrained outputs wherever the compiler likes
        # (observed: gathered to one device), so place the state
        # explicitly: subtrees mirroring the param pytree (Adam's mu/nu,
        # momentum buffers...) get the tensor-parallel param shardings;
        # everything else (step counts, scalars) is replicated.
        state = jax.jit(tx.init)(params)

        def place(x):
            if _is_param_tree(x):
                return jax.tree.map(jax.device_put, x, param_shardings)
            if getattr(x, "size", 0) > 1 and getattr(x, "ndim", 0) >= 2:
                raise ValueError(
                    "optimizer state holds a non-scalar tensor outside a "
                    "param-mirroring subtree (optax.masked / "
                    "multi_transform / factored state?); automatic "
                    "placement would replicate it onto every device — "
                    "place this optimizer's state explicitly instead of "
                    "using make_train_step_optax's init_fn")
            return jax.device_put(x, NamedSharding(mesh, P()))

        return jax.tree.map(place, state, is_leaf=_is_param_tree)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg, mesh))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # opt_state sharding: None = inherit from the committed arrays that
    # init_fn produced (propagated from param shardings).
    return init_fn, jax.jit(
        step,
        in_shardings=(param_shardings, None, data_sharding),
        out_shardings=(param_shardings, None, NamedSharding(mesh, P())),
    )
