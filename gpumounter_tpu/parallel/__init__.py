from gpumounter_tpu.parallel.mesh import build_mesh, mesh_shape_for
from gpumounter_tpu.parallel.train_step import make_train_step, shard_params

__all__ = ["build_mesh", "mesh_shape_for", "make_train_step", "shard_params"]
