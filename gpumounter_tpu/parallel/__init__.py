from gpumounter_tpu.parallel.mesh import build_mesh, mesh_shape_for
from gpumounter_tpu.parallel.ring_attention import ring_attention
from gpumounter_tpu.parallel.tp_attention import tp_flash_attention
from gpumounter_tpu.parallel.train_step import make_train_step, shard_params

__all__ = ["build_mesh", "mesh_shape_for", "make_train_step",
           "ring_attention", "shard_params", "tp_flash_attention"]
