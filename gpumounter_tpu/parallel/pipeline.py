"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

TPU-first: each device on the "pipe" axis owns one stage's parameters;
activations move stage-to-stage with `jax.lax.ppermute` (neighbor ICI
transfers) inside a `lax.fori_loop` over M + P - 1 ticks, all under one
jit — no host round-trips, static shapes throughout (SURVEY.md §2b: the
collective is the JAX primitive, not a comm library).

Schedule: at tick t, stage p computes microbatch (t - p) when
0 ≤ t - p < M: stage 0 feeds itself from the microbatch buffer, later
stages consume the activation ppermuted from stage p-1 at tick end. The
last stage scatters its result into the output buffer, which is summed
across the ring at the end (only the last stage wrote nonzero rows).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name: str):
    """Per-device body under shard_map.

    stage_params: this stage's params, leading axis stripped (block of 1).
    x_micro: (M, mb, *rest) — full microbatch buffer, replicated.
    Returns (M, mb, *rest) outputs, replicated (psum at the end).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    # shard_map delivers this stage's block with the stage axis kept
    # (leading size 1); strip it so stage_fn sees plain per-stage params.
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    n_micro = x_micro.shape[0]
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    out_buf = jnp.zeros_like(x_micro, dtype=jnp.float32)
    recv = jnp.zeros(x_micro.shape[1:], x_micro.dtype)

    def tick(t, carry):
        recv, out_buf = carry
        m = t - stage                      # microbatch index for this stage
        active = (m >= 0) & (m < n_micro)
        # Stage 0 reads its own input; others use the received activation.
        own = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(m, 0, n_micro - 1), axis=0, keepdims=False)
        x_in = jnp.where(stage == 0, own, recv)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # Last stage records its finished microbatch.
        is_last = stage == n_stages - 1
        write_idx = jnp.clip(m, 0, n_micro - 1)
        contribution = jnp.where(active & is_last,
                                 y.astype(jnp.float32),
                                 jnp.zeros_like(y, jnp.float32))
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf,
            jax.lax.dynamic_index_in_dim(out_buf, write_idx, 0, False)
            + contribution,
            write_idx, axis=0)
        # Rotate activations forward one stage.
        recv = jax.lax.ppermute(y, axis_name, perm_fwd)
        return recv, out_buf

    recv, out_buf = jax.lax.fori_loop(
        0, n_micro + n_stages - 1, tick, (recv, out_buf))
    # Only the last stage holds real outputs; share them with every stage.
    return jax.lax.psum(out_buf, axis_name).astype(x_micro.dtype)


def pipeline_apply(stage_params, x: jax.Array, mesh: Mesh, stage_fn,
                   *, n_micro: int, pipe_axis: str = "pipe") -> jax.Array:
    """Run x (B, *rest) through P pipeline stages with M microbatches
    split along the batch axis.

    stage_params: pytree whose leaves have a leading stage axis of size P,
    sharded over `pipe_axis`. stage_fn(params_for_stage, x_mb) -> y_mb
    (same shape). B must divide by n_micro. Differentiable: the tick
    loop has static bounds (lowers to scan) and the stage rotation is a
    ppermute, so jax.grad of a loss on the output back-propagates
    through the whole schedule — make_pipeline_train_step relies on it.
    """
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    x_micro = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    body = partial(_pipeline_local, stage_fn=stage_fn, axis_name=pipe_axis)
    param_specs = jax.tree.map(lambda _: P(pipe_axis), stage_params)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)
    y_micro = fn(stage_params, x_micro)
    return y_micro.reshape(x.shape)


def shard_stage_params(stage_params, mesh: Mesh, pipe_axis: str = "pipe"):
    return jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, NamedSharding(mesh, P(pipe_axis))), stage_params)
