"""Pipeline parallelism: microbatch schedules over a mesh axis.

TPU-first: each device on the "pipe" axis owns its stage parameters;
activations move stage-to-stage with `jax.lax.ppermute` (neighbor ICI
transfers) inside a `lax.fori_loop` with static bounds, all under one
jit — no host round-trips, static shapes throughout (SURVEY.md §2b: the
collective is the JAX primitive, not a comm library).

Two schedules behind one entry point (`n_virtual`):

* **GPipe** (`n_virtual=1`): P devices = P stages; microbatch m runs on
  stage p at tick m + p. Bubble: P - 1 of M + P - 1 ticks.
* **Interleaved / circular** (`n_virtual=v > 1`): each device owns v
  non-contiguous stage *chunks* (logical stage s = k·P + d lives on
  device d, chunk k), the schedule Megatron-LM calls "interleaved 1F1B"
  and the scaling literature calls circular pipelining. A device runs
  chunk k of microbatch m at tick

      t = d + (m mod P) + P·(v·⌊m/P⌋ + k)

  which (a) assigns every device at most one (chunk, microbatch) per
  tick — (m, k) ↔ (t - d) is a bijection via the mixed-radix
  decomposition r + P·(j·v + k) — and (b) keeps the data motion a
  single forward ring ppermute per tick, because the producing tick of
  stage s is always exactly one before the consuming tick of stage
  s + 1 (same chunk → next device; chunk boundary → device P-1 wraps
  to device 0 at the same +1 tick). Bubble: still P - 1 ticks, but of
  M·v + P - 1 total — each tick is 1/v of a GPipe tick's work, so the
  bubble *fraction* drops from (P-1)/(M+P-1) toward (P-1)/(M·v+P-1).

Both schedules differentiate: the tick loop lowers to scan and the
rotation is ppermute, so jax.grad back-propagates through the whole
schedule (the backward of a circular forward is the mirrored circular
backward XLA derives). What interleaving buys is the BUBBLE fraction,
not memory: jax.grad still saves residuals for every tick, so peak
activation memory scales with the total microbatch count M, like
GPipe and unlike a hand-scheduled 1F1B (which caps in-flight
activations at ~P). Size M accordingly, or wrap stage_fn in
jax.checkpoint to trade the residuals for recompute.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def schedule_info(n_micro: int, n_stages: int, n_virtual: int = 1) -> dict:
    """Bubble accounting for a (M, P, v) pipeline schedule.

    ticks: total schedule length; busy device-ticks are M·v per device,
    so bubble_fraction = 1 - M·v / ticks = (P - 1) / ticks.
    """
    ticks = n_micro * n_virtual + n_stages - 1
    return {
        "ticks": ticks,
        "bubble_ticks": n_stages - 1,
        "bubble_fraction": (n_stages - 1) / ticks,
    }


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name: str,
                    n_virtual: int):
    """Per-device body under shard_map.

    stage_params: this device's chunks, leading axes (1, v) (block of 1
    on the pipe axis, then the chunk axis).
    x_micro: (M, mb, *rest) — full microbatch buffer, replicated.
    Returns (M, mb, *rest) outputs, replicated (psum at the end).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    # shard_map delivers this device's block with the pipe axis kept
    # (leading size 1); strip it, keeping the chunk axis (v, ...).
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    n_micro = x_micro.shape[0]
    v = n_virtual
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    out_buf = jnp.zeros_like(x_micro, dtype=jnp.float32)
    recv = jnp.zeros(x_micro.shape[1:], x_micro.dtype)

    def tick(t, carry):
        recv, out_buf = carry
        # Decode (microbatch m, chunk k) from u = t - stage via the
        # mixed-radix split u = r + P·(j·v + k). For v=1 this reduces
        # to m = u, k = 0 — exactly the GPipe schedule.
        u = t - stage
        uc = jnp.maximum(u, 0)
        r = uc % n_stages
        q = uc // n_stages
        k = q % v
        j = q // v
        m = j * n_stages + r
        active = (u >= 0) & (m < n_micro)
        chunk = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, k, axis=0, keepdims=False),  # k = q % v is in [0, v)
            stage_params)
        # The first logical stage reads its own input; all others use
        # the received activation.
        own = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(m, 0, n_micro - 1), axis=0, keepdims=False)
        is_first = (stage == 0) & (k == 0)
        x_in = jnp.where(is_first, own, recv)
        y = stage_fn(chunk, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # The last logical stage records its finished microbatch.
        is_last = (stage == n_stages - 1) & (k == v - 1)
        write_idx = jnp.clip(m, 0, n_micro - 1)
        contribution = jnp.where(active & is_last,
                                 y.astype(jnp.float32),
                                 jnp.zeros_like(y, jnp.float32))
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf,
            jax.lax.dynamic_index_in_dim(out_buf, write_idx, 0, False)
            + contribution,
            write_idx, axis=0)
        # Rotate activations forward one stage (chunk wrap P-1 → 0
        # rides the same ring edge).
        recv = jax.lax.ppermute(y, axis_name, perm_fwd)
        return recv, out_buf

    recv, out_buf = jax.lax.fori_loop(
        0, n_micro * v + n_stages - 1, tick, (recv, out_buf))
    # Only the last stage holds real outputs; share them with every stage.
    return jax.lax.psum(out_buf, axis_name).astype(x_micro.dtype)


def pipeline_apply(stage_params, x: jax.Array, mesh: Mesh, stage_fn,
                   *, n_micro: int, pipe_axis: str = "pipe",
                   n_virtual: int = 1) -> jax.Array:
    """Run x (B, *rest) through the pipeline with M microbatches split
    along the batch axis.

    stage_params: pytree whose leaves carry a leading device axis of
    size P (GPipe, n_virtual=1) or leading axes (P, v) (interleaved,
    n_virtual=v), sharded over `pipe_axis`. stage_fn(chunk_params,
    x_mb) -> y_mb (same shape) where chunk_params has the leading
    axes stripped. B must divide by n_micro; the interleaved schedule
    additionally needs n_micro % P == 0 (microbatches cycle the ring
    in groups of P). Differentiable end to end — the train step relies
    on it.
    """
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    n_stages = mesh.shape[pipe_axis]
    if n_virtual < 1:
        raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
    if n_virtual > 1 and n_micro % n_stages:
        raise ValueError(
            f"interleaved schedule needs n_micro ({n_micro}) divisible "
            f"by the stage count ({n_stages})")
    if n_virtual == 1:
        # Lift (P, ...) leaves to the unified (P, v=1, ...) layout.
        stage_params = jax.tree.map(lambda a: a[:, None], stage_params)
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != n_stages or leaf.shape[1] != n_virtual:
            raise ValueError(
                f"stage param leaf has leading shape {leaf.shape[:2]}, "
                f"expected ({n_stages}, {n_virtual})")
    x_micro = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    body = partial(_pipeline_local, stage_fn=stage_fn,
                   axis_name=pipe_axis, n_virtual=n_virtual)
    param_specs = jax.tree.map(lambda _: P(pipe_axis), stage_params)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)
    y_micro = fn(stage_params, x_micro)
    return y_micro.reshape(x.shape)


def shard_stage_params(stage_params, mesh: Mesh, pipe_axis: str = "pipe"):
    return jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, NamedSharding(mesh, P(pipe_axis))), stage_params)
