"""Mixture-of-Experts FFN with expert parallelism over a mesh axis.

TPU-first: experts are sharded over the "expert" mesh axis with
NamedSharding; routing uses dense one-hot dispatch/combine einsums
(Switch-style top-1), so the whole layer is three MXU-friendly einsums and
XLA inserts the all-to-all/psum collectives implied by the shardings —
no hand-written communication (scaling-book recipe; SURVEY.md §2b).

Capacity-less formulation: every token's hidden is computed against its
expert via the dispatch one-hot, which keeps shapes static (XLA-friendly)
at the cost of E× compute of a capacity router — the right trade for a
probe/e2e workload whose job is to light up chips, not to train cheaply.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key: jax.Array, n_experts: int, d_model: int,
                    d_ff: int, dtype=jnp.bfloat16) -> dict:
    k_router, k_w1, k_w2 = jax.random.split(key, 3)
    scale = 0.02
    return {
        "router": (jax.random.normal(k_router, (d_model, n_experts),
                                     jnp.float32) * scale),
        "w1": (jax.random.normal(k_w1, (n_experts, d_model, d_ff),
                                 jnp.float32) * scale).astype(dtype),
        "w2": (jax.random.normal(k_w2, (n_experts, d_ff, d_model),
                                 jnp.float32) * scale).astype(dtype),
    }


def moe_param_specs(axis: str = "expert") -> dict:
    """Expert dim sharded over `axis`; router replicated. The
    standalone MoE step uses a dedicated "expert" mesh axis; the
    flagship probe rides the tensor-parallel "model" axis instead
    (parallel/train_step.param_specs)."""
    return {
        "router": P(None, None),
        "w1": P(axis, None, None),
        "w2": P(axis, None, None),
    }


def shard_moe_params(params: dict, mesh: Mesh) -> dict:
    specs = moe_param_specs()
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def moe_ffn(params: dict, x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Top-1 routed FFN. x: (tokens, d_model) → (tokens, d_model).

    Returns (output, aux_loss) where aux_loss is the Switch load-balancing
    loss (mean fraction · mean router prob per expert, scaled by E).
    """
    n_tokens, d_model = x.shape
    n_experts = params["router"].shape[1]
    logits = x.astype(jnp.float32) @ params["router"]      # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                # (T,)
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=x.dtype)  # (T, E)
    gate = jnp.take_along_axis(probs, expert_idx[:, None],
                               axis=1).astype(x.dtype)     # (T, 1)

    # dispatch: (E, T, d) — token rows zeroed except at their expert;
    # sharded einsums put each expert's slice on its own devices.
    dispatched = jnp.einsum("te,td->etd", onehot, x)
    h = jnp.einsum("etd,edf->etf", dispatched, params["w1"])
    h = jax.nn.gelu(h)
    out_e = jnp.einsum("etf,efd->etd", h, params["w2"])
    combined = jnp.einsum("etd,te->td", out_e, onehot) * gate

    # Switch aux loss: encourages uniform routing.
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)    # (E,)
    prob_mean = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac * prob_mean)
    return combined, aux


def make_moe_step(mesh: Mesh, n_experts: int, d_model: int, d_ff: int,
                  lr: float = 1e-2):
    """Jitted MoE train step over (data, expert) mesh axes: tokens sharded
    on "data", experts on "expert"."""
    specs = moe_param_specs()
    param_shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    x_sharding = NamedSharding(mesh, P("data", None))

    def loss_fn(params, x, target):
        out, aux = moe_ffn(params, x)
        mse = jnp.mean((out.astype(jnp.float32)
                        - target.astype(jnp.float32)) ** 2)
        return mse + 0.01 * aux

    def step(params, x, target):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, target)
        params = jax.tree.map(
            lambda p, g: (p.astype(jnp.float32)
                          - lr * g.astype(jnp.float32)).astype(p.dtype),
            params, grads)
        return params, loss

    return jax.jit(step,
                   in_shardings=(param_shardings, x_sharding, x_sharding),
                   out_shardings=(param_shardings, NamedSharding(mesh, P())))
