"""Tensor-parallel attention: heads sharded over a mesh axis.

Attention is embarrassingly parallel over heads — no collectives are
needed, only placement — but a Pallas kernel cannot be partitioned by
XLA's automatic sharding (a custom call is opaque to the partitioner),
so under jit-with-shardings the kernel would force a gather to one
device. This wrapper runs the kernel under shard_map instead: each
device gets its head shard and runs the kernel locally, which is the
TPU-idiomatic way to combine tp sharding with custom kernels
(scaling-book recipe: mesh + shardings; shard_map where the compiler
cannot infer).

GQA composes when the kv heads divide evenly over the same axis
(H_kv % axis_size == 0); each shard then holds whole q-head groups and
the kernel's zero-copy group mapping works per shard unchanged.

Combine with ring_attention for sequences too long for one device: tp
over heads x ring over sequence is a 2-D mesh with this wrapper's
in_specs extended by the seq axis.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def tp_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                       mesh: Mesh, *, head_axis: str = "model",
                       causal: bool = True, scale: float | None = None,
                       backend: str = "auto",
                       window: int | None = None,
                       softcap: float | None = None) -> jax.Array:
    """(B, H, L, D) attention with H sharded over `mesh`'s `head_axis`.

    q/k/v may be unsharded (shard_map places them) or already sharded
    with P(None, head_axis, None, None). GQA: k/v may carry fewer heads;
    both H and H_kv must divide the axis size evenly so every shard
    holds whole groups. Dispatch (kernel vs fused XLA) happens per
    shard via the public flash_attention entry.
    """
    # Lazy, like every other flash_attention consumer: keeps the Pallas
    # import out of mesh-only startup paths.
    from gpumounter_tpu.ops.flash_attention import flash_attention

    n_shards = mesh.shape[head_axis]
    h, h_kv = q.shape[1], k.shape[1]
    if h % n_shards or h_kv % n_shards:
        raise ValueError(
            f"heads must divide the {head_axis!r} axis evenly: "
            f"H={h}, H_kv={h_kv}, axis size {n_shards}")
    spec = P(None, head_axis, None, None)
    body = partial(flash_attention, causal=causal, scale=scale,
                   backend=backend, window=window, softcap=softcap)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)


def shard_heads(x: jax.Array, mesh: Mesh,
                head_axis: str = "model") -> jax.Array:
    """Place a (B, H, L, D) array with H split over the mesh axis."""
    return jax.device_put(
        x, NamedSharding(mesh, P(None, head_axis, None, None)))
