"""gpumounter_tpu — TPU-native hot-mount framework for Kubernetes Pods.

A ground-up, TPU-first re-design of the capabilities of GPUMounter
(reference: jason-gideon/GPUMounter): dynamically add/remove accelerator
devices to/from *running* Pods without restart, scheduler-coherently.

Where the reference is Go + NVML (cgo) + cgroup-v1 `devices.allow` writes +
`nsenter` shell-outs, this framework is:

  * Python control plane (master HTTP gateway, per-node worker gRPC daemon,
    allocator, collector) — no NVIDIA stack anywhere in the loop.
  * C++ native layer (``native/``) for the host/kernel boundary: `/dev/accel*`
    discovery, `/proc/*/fd` busy scanning, cgroup-v2 eBPF
    `BPF_PROG_TYPE_CGROUP_DEVICE` programs, and a `setns(2)`+`mknod(2)`
    helper — direct syscalls, no `sh -c` string building.
  * JAX tenant-side library (``gpumounter_tpu.jaxside``) so a running JAX
    process observes hot-mounted chips (`jax.devices()` refresh), plus the
    mesh/topology machinery to resume SPMD work over the new chip set.

Layer map (parity with reference SURVEY.md §1):
  master/    — L1 HTTP API gateway
  rpc/       — L2 RPC contract (protobuf wire-level, reference api.proto parity)
  worker/    — L3 per-node daemon + mount orchestration (reference pkg/server, pkg/util/util.go)
  allocator/ — L4 scheduler-coherent allocation (reference pkg/util/gpu/allocator)
  collector/ — L5 device inventory + pod<->device map (reference pkg/util/gpu/collector)
  cgroup/    — L6 device cgroup grant/revoke, v1 + v2-eBPF (reference pkg/util/cgroup)
  nsutil/    — L6 namespace entry / device-file ops (reference pkg/util/namespace)
  device/    — L7 TPU device layer (replaces reference pkg/device + nvml cgo bindings)
  k8s/       — minimal Kubernetes REST client + fake (replaces client-go usage)
  config/, utils/ — L8 cross-cutting
  jaxside/, models/, ops/, parallel/ — tenant-side JAX visibility + workload
"""

__version__ = "0.1.0"
