"""Fractional chip virtualization (ISSUE 17).

A *share* is (chip, weight, tenant): one physical chip carried by one
slave pod can be split across N tenants, each holding a QoS weight and
an optional rate budget. The pieces:

  * shares.py — the master-side ShareRegistry: the source of truth for
    who holds what fraction of which chip (the "books count shares not
    chips" half of the allocation model), with the payload served at
    GET /shares and the `books()` view chaos invariant 19 compares
    against the kernel policy maps and the worker ledger.

  * packer.py — the SharePacker admission controller: co-locates
    complementary tenants (prefill-heavy with decode-heavy) on already-
    shared chips first, then opens fresh chips, avoiding hosts the
    defragmenter is about to rearrange.

Enforcement lives in cgroup/ebpf.py (policy-map token buckets consulted
in-kernel by the device program) with cgroup/policy.py as the userspace
fallback proving identical admit/deny decisions.
"""
