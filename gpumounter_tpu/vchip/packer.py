"""Co-location admission control: which chips should a fractional
tenant share?

The flagship scenario (FlexNPU, PAPERS.md): a prefill-heavy tenant is
compute-bound in bursts, a decode-heavy tenant is latency-bound and
steady — packed onto the same chips with QoS weights, the pair
recovers utilization headroom that whole-chip granularity wastes. The
packer encodes that preference directly:

  1. already-shared chips whose resident profiles COMPLEMENT the
     request (prefill packs with decode and vice versa) and whose
     booked load leaves room for the new weight — tightest-packed
     (highest load) first, so sharing concentrates instead of
     smearing across the fleet;
  2. then any other shared chip with headroom (same-profile
     co-location is allowed, just not preferred);
  3. then free chips, skipping hosts the capacity plane flags as
     defrag-blocked (the defragmenter is about to rearrange them —
     packing new shares there would undo its plan; the same hint the
     allocator's placement consults, satellite 1);
  4. refuse (PackRefused) when the fleet cannot carry the request —
     a typed refusal the /shares route maps to 409, never a silent
     partial placement.

The packer only DECIDES and books; the caller (master route, bench,
chaos harness) pushes the resulting policy to the enforcement layer.
"""

from __future__ import annotations

from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.vchip.shares import Share, ShareRegistry

logger = get_logger("vchip.packer")

#: profiles that pack well together: bursty-compute with steady-latency
COMPLEMENTS = {"prefill": "decode", "decode": "prefill"}


class PackRefused(RuntimeError):
    """The request cannot be placed: bad arguments, or no chip set
    with enough weight headroom exists."""


class SharePacker:
    def __init__(self, registry: ShareRegistry, cfg=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.cfg = cfg
        self.registry = registry

    def admit(self, namespace: str, pod: str, profile: str, chips: int,
              weight: int, rate_budget: int = 0,
              inventory: dict[str, str] | None = None,
              blocked_hosts: frozenset[str] | set[str] = frozenset(),
              excluded_hosts: frozenset[str] | set[str] = frozenset(),
              probation_hosts: frozenset[str] | set[str] = frozenset(),
              ) -> list[Share]:
        """Book `chips` fractional shares for tenant namespace/pod.

        inventory: chip uuid -> node for every chip the caller may
        place on (free chips plus already-shared ones); the packer
        never invents chips. blocked_hosts: hosts the defragmenter
        needs quiet — free chips there are last-resort only.
        excluded_hosts: a HARD exclusion (health-plane quarantine) —
        chips there are never candidates, even when refusal is the
        alternative. probation_hosts: placeable but deprioritized
        (rehabilitating nodes rank after every equivalent candidate).

        Returns the booked shares (the caller turns each into a policy
        map entry). All-or-nothing: a refusal books nothing.
        """
        capacity = int(self.cfg.vchip_weight_capacity)
        if chips <= 0:
            raise PackRefused(f"chips must be positive, got {chips}")
        if not 1 <= weight <= capacity:
            raise PackRefused(
                f"weight {weight} outside 1..{capacity} "
                f"(vchip_weight_capacity)")
        if rate_budget < 0:
            raise PackRefused(f"rate_budget must be >= 0, got {rate_budget}")
        inventory = dict(inventory or {})
        shared = self.registry.shared_chips()
        held = {s.chip_uuid for s in self.registry.by_tenant(namespace, pod)}
        want = COMPLEMENTS.get(profile)

        complementary: list[tuple[int, int, str]] = []
        other_shared: list[tuple[int, int, str]] = []
        for uuid, holders in shared.items():
            if uuid in held:
                continue  # re-grants go through admit on the same chip
            load = sum(s.weight for s in holders)
            if load + weight > capacity:
                continue
            node = holders[0].node
            if node in excluded_hosts:
                continue  # quarantined: never a candidate
            if uuid not in inventory:
                inventory[uuid] = node
            profiles = {s.profile for s in holders}
            # probation last within its class, then tightest-packed
            # first (sort key -load)
            penalty = 1 if node in probation_hosts else 0
            if want is not None and want in profiles \
                    and profile not in profiles:
                complementary.append((penalty, -load, uuid))
            else:
                other_shared.append((penalty, -load, uuid))
        taken = set(held) | set(shared)
        placeable = {u: node for u, node in inventory.items()
                     if node not in excluded_hosts}
        free_clear = sorted(u for u, node in placeable.items()
                            if u not in taken and node not in blocked_hosts
                            and node not in probation_hosts)
        free_probation = sorted(u for u, node in placeable.items()
                                if u not in taken
                                and node not in blocked_hosts
                                and node in probation_hosts)
        free_blocked = sorted(u for u, node in placeable.items()
                              if u not in taken and node in blocked_hosts)

        ranked = ([u for *_, u in sorted(complementary)]
                  + [u for *_, u in sorted(other_shared)]
                  + free_clear + free_probation + free_blocked)
        if len(ranked) < chips:
            raise PackRefused(
                f"need {chips} chip(s) with weight headroom {weight}, "
                f"only {len(ranked)} available "
                f"(shared with room: {len(complementary) + len(other_shared)}, "
                f"free: {len(free_clear) + len(free_probation) + len(free_blocked)})")
        chosen = ranked[:chips]
        booked: list[Share] = []
        try:
            for uuid in chosen:
                booked.append(self.registry.add(Share(
                    namespace=namespace, pod=pod, chip_uuid=uuid,
                    node=inventory[uuid], weight=weight,
                    rate_budget=rate_budget, profile=profile)))
        except Exception:
            for share in booked:  # all-or-nothing
                self.registry.remove(share.namespace, share.pod,
                                     share.chip_uuid)
            raise
        n_coloc = sum(1 for u in chosen if u in shared)
        logger.info(
            "admitted %d share(s) for %s/%s (profile=%s weight=%d "
            "budget=%d): %d co-located, %d fresh%s",
            chips, namespace, pod, profile, weight, rate_budget,
            n_coloc, chips - n_coloc,
            " [used defrag-blocked hosts]" if any(
                u in free_blocked for u in chosen) else "")
        return booked

    def release(self, namespace: str, pod: str) -> list[Share]:
        """Drop every share a tenant holds; returns what was removed
        so the caller can clear the matching policy entries."""
        return self.registry.remove_tenant(namespace, pod)
