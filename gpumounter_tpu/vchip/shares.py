"""Master-side share books: who holds what fraction of which chip.

The registry is deliberately dumb storage with indexes — admission
logic (who MAY take a share of which chip) lives in packer.py, and
enforcement (what an admitted tenant may actually do) lives in the
cgroup policy maps. What the registry guarantees:

  * every share is bounded by cfg.vchip_max_shares (a runaway client
    cannot grow the books without bound — same discipline as the
    tenant plane's cardinality caps);
  * per-chip load (sum of weights) is tracked so the packer's
    "load + weight <= vchip_weight_capacity" check is O(1);
  * `books()` exposes tenant -> {chip: (weight, rate_budget)} in the
    SAME packed shape the kernel policy maps and the worker ledger
    carry, so chaos invariant 19 can compare the three ledgers
    value-for-value after every scenario.

Share ids are stable, human-readable (`<namespace>/<pod>/<chip>`), and
the natural idempotency key: re-admitting the same (tenant, chip) is a
re-grant — the weight/budget are updated in place, mirroring the O(1)
map_update the enforcement layer does on warm re-grants.

Gauges are fleet-scalar only (no tenant/chip labels) — the per-share
detail rides the JSON plane at GET /shares, exactly like /capacity.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("vchip.shares")

SHARES_SCHEMA = "tpumounter-shares/1"

SHARES_ACTIVE = REGISTRY.gauge(
    "tpumounter_vchip_shares_active",
    "Fractional chip shares currently on the books")
SHARED_CHIPS = REGISTRY.gauge(
    "tpumounter_vchip_shared_chips",
    "Physical chips currently split across more than one tenant")
SHARE_ADMITS = REGISTRY.counter(
    "tpumounter_vchip_share_admits_total",
    "Shares admitted onto the books (re-grants of an existing "
    "(tenant, chip) share count too — they are the O(1) warm path)")
SHARE_RELEASES = REGISTRY.counter(
    "tpumounter_vchip_share_releases_total",
    "Shares released from the books")


class ShareLimitError(RuntimeError):
    """The books are full (cfg.vchip_max_shares)."""


@dataclass(frozen=True)
class Share:
    """One tenant's fraction of one chip."""
    namespace: str
    pod: str
    chip_uuid: str
    node: str
    weight: int
    rate_budget: int  # 0 = unmetered
    profile: str      # "prefill" | "decode" | "balanced" | free-form
    created_at: float = field(default_factory=time.time)

    @property
    def tenant(self) -> str:
        return f"{self.namespace}/{self.pod}"

    @property
    def share_id(self) -> str:
        return f"{self.namespace}/{self.pod}/{self.chip_uuid}"

    def to_json(self) -> dict:
        return {
            "tenant": self.tenant,
            "namespace": self.namespace,
            "pod": self.pod,
            "chip_uuid": self.chip_uuid,
            "node": self.node,
            "weight": self.weight,
            "rate_budget": self.rate_budget,
            "profile": self.profile,
            "created_at": round(self.created_at, 3),
        }


class ShareRegistry:
    def __init__(self, cfg=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.cfg = cfg
        self._lock = OrderedLock("vchip.shares")
        self._shares: dict[str, Share] = {}
        self._by_chip: dict[str, set[str]] = {}

    # --- mutation ---

    def add(self, share: Share) -> Share:
        """Put a share on the books. Re-adding an existing
        (tenant, chip) replaces it in place (warm re-grant) and does
        not consume a new books slot."""
        with self._lock:
            sid = share.share_id
            if sid not in self._shares and \
                    len(self._shares) >= int(self.cfg.vchip_max_shares):
                raise ShareLimitError(
                    f"share books full ({self.cfg.vchip_max_shares}); "
                    f"refusing {sid}")
            self._shares[sid] = share
            self._by_chip.setdefault(share.chip_uuid, set()).add(sid)
            self._update_gauges_locked()
        SHARE_ADMITS.inc()
        return share

    def remove(self, namespace: str, pod: str, chip_uuid: str) -> bool:
        with self._lock:
            removed = self._remove_locked(
                f"{namespace}/{pod}/{chip_uuid}")
            self._update_gauges_locked()
        if removed:
            SHARE_RELEASES.inc()
        return removed

    def remove_tenant(self, namespace: str, pod: str) -> list[Share]:
        """Drop every share a tenant holds (pod deletion, revoke-all).
        Returns the shares removed so callers can clear the matching
        policy entries."""
        prefix = f"{namespace}/{pod}/"
        with self._lock:
            victims = [s for sid, s in self._shares.items()
                       if sid.startswith(prefix)]
            for share in victims:
                self._remove_locked(share.share_id)
            self._update_gauges_locked()
        if victims:
            SHARE_RELEASES.inc(float(len(victims)))
        return victims

    def _remove_locked(self, sid: str) -> bool:
        share = self._shares.pop(sid, None)
        if share is None:
            return False
        holders = self._by_chip.get(share.chip_uuid)
        if holders is not None:
            holders.discard(sid)
            if not holders:
                self._by_chip.pop(share.chip_uuid, None)
        return True

    def _update_gauges_locked(self) -> None:
        SHARES_ACTIVE.set(float(len(self._shares)))
        SHARED_CHIPS.set(float(sum(
            1 for sids in self._by_chip.values() if len(sids) > 1)))

    # --- queries ---

    def get(self, namespace: str, pod: str,
            chip_uuid: str) -> Share | None:
        with self._lock:
            return self._shares.get(f"{namespace}/{pod}/{chip_uuid}")

    def by_chip(self, chip_uuid: str) -> list[Share]:
        with self._lock:
            return [self._shares[sid]
                    for sid in sorted(self._by_chip.get(chip_uuid, ()))]

    def by_tenant(self, namespace: str, pod: str) -> list[Share]:
        prefix = f"{namespace}/{pod}/"
        with self._lock:
            return [s for sid, s in sorted(self._shares.items())
                    if sid.startswith(prefix)]

    def chip_load(self, chip_uuid: str) -> int:
        """Sum of weights booked on a chip."""
        with self._lock:
            return sum(self._shares[sid].weight
                       for sid in self._by_chip.get(chip_uuid, ()))

    def shared_chips(self) -> dict[str, list[Share]]:
        """chip uuid -> its shares, for every chip on the books."""
        with self._lock:
            return {uuid: [self._shares[sid] for sid in sorted(sids)]
                    for uuid, sids in sorted(self._by_chip.items())}

    def books(self) -> dict[str, dict[str, tuple[int, int]]]:
        """tenant -> {chip uuid: (weight, rate_budget)} — the view
        chaos invariant 19 compares against the kernel policy maps and
        the worker ledger's share records."""
        out: dict[str, dict[str, tuple[int, int]]] = {}
        with self._lock:
            for share in self._shares.values():
                out.setdefault(share.tenant, {})[share.chip_uuid] = (
                    share.weight, share.rate_budget)
        return out

    def payload(self) -> dict:
        """The GET /shares response body."""
        with self._lock:
            shares = [self._shares[sid].to_json()
                      for sid in sorted(self._shares)]
            chips = {}
            for uuid, sids in sorted(self._by_chip.items()):
                load = sum(self._shares[sid].weight for sid in sids)
                chips[uuid] = {
                    "node": next(iter(
                        self._shares[sid].node for sid in sorted(sids))),
                    "tenants": len(sids),
                    "load": load,
                    "headroom": max(
                        0, int(self.cfg.vchip_weight_capacity) - load),
                    "profiles": sorted({self._shares[sid].profile
                                        for sid in sids}),
                }
        return {
            "schema": SHARES_SCHEMA,
            "at": time.time(),
            "weight_capacity": int(self.cfg.vchip_weight_capacity),
            "max_shares": int(self.cfg.vchip_max_shares),
            "shares": shares,
            "chips": chips,
            "totals": {
                "shares": len(shares),
                "chips": len(chips),
                "shared_chips": sum(
                    1 for c in chips.values() if c["tenants"] > 1),
            },
        }
