"""Gray-failure detection and node quarantine plane (ISSUE 18).

The recovery controller (gpumounter_tpu/recovery/) only acts on
confirmed-DEAD nodes; this package catches the node that is alive but
limping — mounts 50x slower, drops a fraction of RPCs — scores it from
the fleet telemetry the collector already federates plus an active
canary probe, and quarantines it softly (no placements, warm pool
drained, defrag non-destination) without ever evacuating it.
"""

from gpumounter_tpu.health.plane import (
    STATES,
    CanaryProber,
    HealthPlane,
)

__all__ = ["STATES", "CanaryProber", "HealthPlane"]
