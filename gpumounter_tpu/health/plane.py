"""Per-node gray-failure scorer, quarantine state machine, canary prober.

The recovery plane's world is binary — a node is reachable or it is
dead — because that is the only verdict its positive-corroboration
rules (registry gone + Node NotReady) can prove. Production incidents
are dominated by the third state neither verdict covers: the *limping*
node that answers every liveness probe while mounting 50x slower,
dropping a fraction of RPCs, or fsyncing its ledger at disk-timeout
speeds. Such a node passes recovery's checks forever and keeps
receiving placements, silently burning the mount-latency SLO fleet-wide
(the partial/fail-slow taxonomies in PAPERS.md; GPUMounter itself
assumes reachable-or-not).

Two signal sources drive a per-node state machine

    healthy -> suspect -> quarantined -> rehabilitating -> healthy

* **passive outlier scoring** over the node entries the FleetCollector
  already federates: per-node mount p95 vs the fleet median, mount
  error ratios, and circuit-breaker state from the RPC plane. The
  scorer is a collect-pass observer exactly like the capacity plane —
  wired as `fleet.health`, exception-isolated, and *fail-open*: stale
  entries freeze a node's counters (no signal is not a bad signal, per
  the capacity plane's `capacity_unknown` convention), and a pass in
  which most of the fleet failed to collect is skipped outright — a
  master-side collector bug must not quarantine the fleet.
* an **active canary prober** that periodically drives a real synthetic
  mount -> verify -> unmount through the full worker path (grant,
  mknod, ledger) against a reserved canary pod on the node. Canary
  probes target the decision-relevant set (suspect / quarantined /
  rehabilitating nodes): the passive scorer is what watches the healthy
  herd; the canary is what *proves* a verdict either way.

Quarantine is **soft and reversible**, unlike evacuation: nothing is
unmounted and no tenant is touched. Consumers read
:meth:`HealthPlane.excluded_hosts` (never raises; degrades to the empty
set) — the SharePacker refuses quarantined hosts outright, the defrag
planner treats them as non-destinations, and the fleet collector tells
the node's worker to drain its warm holders via the CollectTelemetry
pull. The recovery controller is explicitly taught quarantined != dead:
it keeps probing a quarantined node under its normal
positive-corroboration rules, so a quarantined node that *then* dies is
evacuated normally, and a gray one never is.

Flap damping: hysteresis windows in both directions (N consecutive bad
passes to demote, M consecutive clean passes to promote) plus a
fleet-wide quarantine budget — the scorer never quarantines more than
`health_quarantine_budget` of the fleet on its own (manual operator
quarantines are exempt: the budget guards against scorer bugs, not
operators). Rehabilitation requires `health_rehab_canary_passes`
consecutive canary passes and re-enters through a placement-
deprioritized probation tier (`rehabilitating`) before the node is
trusted again.

Breaker/canary dedupe: canary probes ride the breaker-aware client, so
a failing canary trips the node's CircuitBreaker — the same incident
must not count as evidence twice (once as canary failure, once as
breaker state). While canary-failure evidence is active for a node the
scorer suppresses the `breaker_open` signal; real-traffic signals
(p95 outlier, error ratio) still count.

Quarantine state persists through the `store/` seam
(save_health_state / load_health_state) so a master shard takeover
rebuilds the quarantine set instead of un-quarantining the fleet.

Every transition lands in the flight recorder (kind="health") carrying
the concrete signals that caused it — chaos invariant 20 audits exactly
that trail.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from gpumounter_tpu.faults import failpoints
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("health.plane")

#: the bounded state vocabulary (metric label; node names never ride
#: labels — they ride the /health/nodes JSON pane).
STATES = ("healthy", "suspect", "quarantined", "rehabilitating")

NODE_HEALTH_STATE = REGISTRY.gauge(
    "tpumounter_node_health_state",
    "Nodes per gray-failure health state (healthy / suspect / "
    "quarantined / rehabilitating); node names ride GET /health/nodes")
CANARY_PROBES = REGISTRY.counter(
    "tpumounter_canary_probes_total",
    "Canary mount->verify->unmount probes driven through the full "
    "worker path")
CANARY_FAILURES = REGISTRY.counter(
    "tpumounter_canary_failures_total",
    "Canary probes that failed (mount refused, chip unhealthy, or "
    "transport error)")
QUARANTINE_TRANSITIONS = REGISTRY.counter(
    "tpumounter_quarantine_transitions_total",
    "Health state-machine transitions by (from_state, to_state) — "
    "bounded by the 4-state vocabulary")
SCORER_SKIPS = REGISTRY.counter(
    "tpumounter_health_scorer_skips_total",
    "Whole scoring passes skipped fail-open (collector staleness / "
    "plane disabled)")
BUDGET_DENIALS = REGISTRY.counter(
    "tpumounter_quarantine_budget_denials_total",
    "Automatic quarantine verdicts suppressed by the fleet-wide "
    "quarantine budget")


def _flight():
    from gpumounter_tpu.obs.flight import FLIGHT
    return FLIGHT


@dataclass
class _NodeRecord:
    """One node's scoring counters. Counters are consecutive-pass
    streaks — the hysteresis windows — not lifetime totals."""

    state: str = "healthy"
    since: float = field(default_factory=time.time)
    reason: str = ""
    signals: list = field(default_factory=list)
    #: consecutive bad scoring passes (drives healthy->suspect->quarantined)
    strikes: int = 0
    #: consecutive clean scoring passes (drives suspect->healthy)
    clear: int = 0
    #: consecutive canary passes / failures (rehab gate + active signal)
    canary_ok: int = 0
    canary_fails: int = 0
    canary_detail: str = ""
    #: consecutive clean passes while rehabilitating (probation gate)
    probation_clear: int = 0
    #: operator-forced: exempt from the budget, never auto-rehabilitated
    manual: bool = False
    #: consecutive quarantined passes with the node's p95 still past the
    #: outlier bar — the SLO-burn attribution that justifies migrating
    #: existing tenants off (recommendation only; the migration itself
    #: rides the existing defrag/migration tooling)
    slo_burn: int = 0
    drain_recommended: bool = False
    #: superseded by a recovery-plane evacuation (the hard verdict wins)
    evacuated: bool = False

    def pane(self) -> dict:
        return {
            "state": self.state,
            "since": round(self.since, 3),
            "reason": self.reason,
            "signals": list(self.signals),
            "strikes": self.strikes,
            "canary": {"consecutive_ok": self.canary_ok,
                       "consecutive_failures": self.canary_fails,
                       "detail": self.canary_detail},
            "manual": self.manual,
            "drain_recommended": self.drain_recommended,
            "evacuated": self.evacuated,
        }


class HealthPlane:
    """The scorer + quarantine state machine. A collect-pass observer
    (``fleet.health``): its bugs must never fail telemetry, and its
    reads (:meth:`excluded_hosts`) must never fail a consumer."""

    def __init__(self, cfg=None, recovery=None, store=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.cfg = cfg
        #: RecoveryController: release() refuses nodes it evacuated, and
        #: evacuation supersedes quarantine (note_evacuated).
        self.recovery = recovery
        #: MasterStore seam: quarantine state survives shard takeover.
        self.store = store
        #: set by the prober; when no canary runs, rehabilitation falls
        #: back to consecutive clean passive passes (documented in FAQ).
        self.canary_active = False
        self._lock = OrderedLock("health.state")
        self._nodes: dict[str, _NodeRecord] = {}
        #: last pass verdict for the pane: "" | "scoring" | "stale"
        self._last_pass = {"at": 0.0, "verdict": "", "fresh": 0,
                           "total": 0, "median_p95_ms": None}

    @property
    def enabled(self) -> bool:
        return bool(self.cfg.health_enabled)

    # --- the passive scorer (collect-pass observer) ---

    def observe(self, nodes: dict[str, dict]) -> None:
        """Score one fleet collection pass. Called by
        FleetCollector.collect_once right after the capacity plane,
        inside the same exception guard."""
        if not self.enabled or not nodes:
            return
        failpoints.fire("health.observe", nodes=len(nodes))
        fresh = {n: e for n, e in nodes.items()
                 if not e.get("stale") and not e.get("error")}
        total = len(nodes)
        # Fail-open (the capacity_unknown convention): when most of the
        # fleet failed to collect the problem is the collector, not the
        # fleet — skip the pass entirely rather than score the survivors
        # against a broken median.
        floor = max(1, int(total * float(self.cfg.health_min_fresh_fraction)))
        if len(fresh) < floor:
            SCORER_SKIPS.inc()
            with self._lock:
                self._last_pass = {"at": time.time(), "verdict": "stale",
                                   "fresh": len(fresh), "total": total,
                                   "median_p95_ms": None}
            logger.warning(
                "health scorer skipped pass fail-open: %d/%d nodes "
                "fresh (< %d)", len(fresh), total, floor)
            return
        median = self._fleet_median_p95(fresh)
        events: list[dict] = []
        with self._lock:
            self._last_pass = {"at": time.time(), "verdict": "scoring",
                               "fresh": len(fresh), "total": total,
                               "median_p95_ms": median}
            # forget healthy records for nodes that left the fleet;
            # quarantined/rehabilitating records survive a node restart
            # (the worker coming back does not make the hardware whole).
            for node in list(self._nodes):
                if node not in nodes and \
                        self._nodes[node].state in ("healthy", "suspect"):
                    del self._nodes[node]
            quarantined = sum(1 for r in self._nodes.values()
                              if r.state == "quarantined")
            budget = max(1, int(total * float(
                self.cfg.health_quarantine_budget)))
            for node in sorted(fresh):
                rec = self._nodes.setdefault(node, _NodeRecord())
                if rec.evacuated:
                    continue  # recovery's hard verdict superseded ours
                signals = self._score(rec, fresh[node], median)
                ev = self._step(node, rec, signals,
                                budget_left=budget - quarantined)
                if ev:
                    events.append(ev)
                    if ev["to"] == "quarantined":
                        quarantined += 1
                    elif ev["from"] == "quarantined":
                        quarantined -= 1
            self._export_gauge_locked()
        # flight records / persistence OUTSIDE health.state: the
        # recorder and store have locks of their own and nothing here
        # needs atomicity with the scoring pass.
        for ev in events:
            self._announce(ev)
        if any(ev["to"] == "quarantined" or ev["from"] == "quarantined"
               for ev in events):
            self._persist()

    def _fleet_median_p95(self, fresh: dict[str, dict]) -> float | None:
        """Median of per-node mount p95 over nodes with enough samples
        to mean anything. None (< 2 contributing nodes) disables the
        outlier signal for the pass — an outlier needs a herd."""
        import statistics
        samples = []
        for entry in fresh.values():
            mount = entry.get("mount") or {}
            if (mount.get("count") or 0) < int(self.cfg.health_min_samples):
                continue
            p95 = mount.get("p95_ms")
            if p95 is not None:
                samples.append(float(p95))
        if len(samples) < 2:
            return None
        return float(statistics.median(samples))

    def _score(self, rec: _NodeRecord, entry: dict,
               median: float | None) -> list[str]:
        """One node's gray-failure signals for this pass. Every string
        names the concrete evidence — it is what the flight record (and
        chaos invariant 20) attributes the quarantine to."""
        signals: list[str] = []
        mount = entry.get("mount") or {}
        count = int(mount.get("count") or 0)
        p95 = mount.get("p95_ms")
        if median is not None and median > 0 \
                and count >= int(self.cfg.health_min_samples) \
                and p95 is not None:
            bar = max(median * float(self.cfg.health_p95_multiplier),
                      median + float(self.cfg.health_p95_floor_ms))
            if float(p95) >= bar:
                signals.append(
                    f"mount_p95_outlier(p95={float(p95):.0f}ms "
                    f"fleet_median={median:.0f}ms bar={bar:.0f}ms)")
        errors = int(mount.get("error") or 0)
        successes = int(mount.get("success") or 0)
        if errors + successes >= int(self.cfg.health_min_samples):
            ratio = errors / float(errors + successes)
            if ratio >= float(self.cfg.health_error_ratio):
                signals.append(
                    f"mount_error_ratio({errors}/{errors + successes})")
        if rec.canary_fails > 0:
            signals.append(f"canary_failures(x{rec.canary_fails}: "
                           f"{rec.canary_detail or 'probe failed'})")
        if entry.get("breaker") == "open":
            if rec.canary_fails > 0:
                # breaker/canary dedupe: the canary's own failed probes
                # are (or may be) what tripped this breaker — one
                # incident is one signal, not two.
                pass
            else:
                signals.append("breaker_open")
        return signals

    def _step(self, node: str, rec: _NodeRecord, signals: list[str],
              budget_left: int) -> dict | None:
        """Advance one node's state machine by one scoring pass; returns
        the transition event (for flight/metrics, emitted outside the
        lock) or None."""
        bad = bool(signals)
        if bad:
            rec.signals = list(signals)
        if rec.state in ("healthy", "suspect"):
            if bad:
                rec.strikes += 1
                rec.clear = 0
                if rec.state == "healthy" and \
                        rec.strikes >= int(self.cfg.health_suspect_strikes):
                    return self._transition(node, rec, "suspect", signals)
                if rec.state == "suspect" and \
                        rec.strikes >= int(self.cfg.health_quarantine_strikes):
                    if budget_left <= 0:
                        BUDGET_DENIALS.inc()
                        logger.warning(
                            "quarantine of %s suppressed: fleet "
                            "quarantine budget exhausted (signals: %s)",
                            node, "; ".join(signals))
                        return None
                    return self._transition(node, rec, "quarantined",
                                            signals)
            else:
                rec.clear += 1
                rec.signals = []
                if rec.clear >= int(self.cfg.health_clear_passes):
                    rec.strikes = 0
                    if rec.state == "suspect":
                        return self._transition(node, rec, "healthy",
                                                ["cleared"])
        elif rec.state == "quarantined":
            # SLO-burn attribution: while quarantined AND still an
            # outlier, the node is actively burning tenant SLOs —
            # after health_drain_burn_passes consecutive such passes
            # the pane recommends migrating its tenants off.
            if any(s.startswith("mount_p95_outlier") for s in signals):
                rec.slo_burn += 1
                if rec.slo_burn >= int(self.cfg.health_drain_burn_passes) \
                        and not rec.drain_recommended:
                    rec.drain_recommended = True
                    return {"node": node, "from": "quarantined",
                            "to": "quarantined", "signals": list(signals),
                            "summary": f"{node}: drain recommended "
                            f"(SLO burn attributed for {rec.slo_burn} "
                            f"passes while quarantined)"}
            else:
                rec.slo_burn = 0
            if rec.manual:
                return None  # operator put it there; operator takes it out
            if not bad:
                rec.clear += 1
                ready = (rec.canary_ok
                         >= int(self.cfg.health_rehab_canary_passes)
                         if self.canary_active else
                         rec.clear >= int(self.cfg.health_rehab_canary_passes))
                if ready:
                    rec.probation_clear = 0
                    return self._transition(node, rec, "rehabilitating",
                                            ["canary_passes"
                                             if self.canary_active
                                             else "clean_passes"])
            else:
                rec.clear = 0
                rec.canary_ok = 0
        elif rec.state == "rehabilitating":
            if bad:
                # flap: straight back to quarantined — no budget check,
                # the node held a quarantine slot moments ago.
                rec.canary_ok = 0
                rec.clear = 0
                return self._transition(node, rec, "quarantined", signals)
            rec.probation_clear += 1
            if rec.probation_clear >= int(self.cfg.health_probation_passes):
                rec.strikes = rec.clear = 0
                rec.slo_burn = 0
                rec.drain_recommended = False
                return self._transition(node, rec, "healthy",
                                        ["probation_complete"])
        return None

    def _transition(self, node: str, rec: _NodeRecord, to: str,
                    signals: list[str]) -> dict:
        """Mutate the record; returns the event the caller announces
        outside the lock."""
        src = rec.state
        rec.state = to
        rec.since = time.time()
        rec.reason = "; ".join(signals)
        if to in ("healthy",):
            rec.signals = []
        return {"node": node, "from": src, "to": to,
                "signals": list(signals),
                "summary": f"{node}: {src} -> {to} ({rec.reason})"}

    def _announce(self, ev: dict) -> None:
        """One transition's observability: bounded transition counter +
        a flight-recorder timeline entry naming the concrete signals
        (the trail chaos invariant 20 audits)."""
        if ev["from"] != ev["to"]:
            QUARANTINE_TRANSITIONS.inc(from_state=ev["from"],
                                       to_state=ev["to"])
        try:
            _flight().record("health", ev["summary"], node=ev["node"],
                             from_state=ev["from"], to_state=ev["to"],
                             signals=list(ev["signals"]))
        except Exception:  # noqa: BLE001 — observability of the observer
            logger.exception("health flight record failed")
        logger.warning("health: %s", ev["summary"])

    def _export_gauge_locked(self) -> None:
        counts = {s: 0 for s in STATES}
        for rec in self._nodes.values():
            if not rec.evacuated:
                counts[rec.state] += 1
        for state, n in counts.items():
            NODE_HEALTH_STATE.set(float(n), state=state)

    # --- the canary's feedback ---

    def record_canary(self, node: str, ok: bool, detail: str = "") -> None:
        """One canary probe outcome. Streak counters only — the scoring
        pass is what turns them into transitions, so canary cadence and
        collect cadence stay decoupled."""
        with self._lock:
            rec = self._nodes.setdefault(node, _NodeRecord())
            if ok:
                rec.canary_ok += 1
                rec.canary_fails = 0
                rec.canary_detail = ""
            else:
                rec.canary_fails += 1
                rec.canary_ok = 0
                rec.canary_detail = detail

    # --- consumer reads (never raise; degrade open) ---

    def excluded_hosts(self) -> frozenset[str]:
        """Hosts no new work may be placed on: the quarantined set.
        Never raises; degrades to the empty set — a broken health plane
        must fail open, not fence the fleet."""
        try:
            with self._lock:
                return frozenset(
                    n for n, r in self._nodes.items()
                    if r.state == "quarantined" and not r.evacuated)
        except Exception:  # noqa: BLE001 — consumer-facing read
            return frozenset()

    def probation_hosts(self) -> frozenset[str]:
        """Rehabilitating nodes: placeable, but deprioritized — new work
        goes there only when nowhere better exists."""
        try:
            with self._lock:
                return frozenset(
                    n for n, r in self._nodes.items()
                    if r.state == "rehabilitating" and not r.evacuated)
        except Exception:  # noqa: BLE001 — consumer-facing read
            return frozenset()

    def is_quarantined(self, node: str) -> bool:
        return node in self.excluded_hosts()

    # --- operator verbs (POST /health/quarantine/<node>) ---

    def quarantine(self, node: str, reason: str = "",
                   actor: str = "operator") -> dict:
        """Manual quarantine. Exempt from the fleet budget (the budget
        guards against scorer bugs, not operators) and never
        auto-rehabilitated — release is manual too."""
        with self._lock:
            rec = self._nodes.setdefault(node, _NodeRecord())
            if rec.evacuated:
                raise ValueError(
                    f"{node} was evacuated by the recovery plane; "
                    f"quarantine would be meaningless")
            if rec.state == "quarantined":
                return rec.pane()
            rec.manual = True
            ev = self._transition(
                node, rec, "quarantined",
                [f"manual({actor}: {reason or 'no reason given'})"])
            self._export_gauge_locked()
            pane = rec.pane()
        self._announce(ev)
        self._persist()
        return pane

    def release(self, node: str, actor: str = "operator") -> dict:
        """Manual release, straight to healthy (the operator has judged
        the node; probation is for the scorer's own verdicts). REFUSES
        a node the recovery plane evacuated — release cannot resurrect
        the dead."""
        # Cross-plane check OUTSIDE health.state: recovery.state must
        # never nest under our lock (keeps the static lock graph
        # acyclic — tools/tpulint lock-order validator).
        recovery_says_dead = self._recovery_evacuated(node)
        with self._lock:
            rec = self._nodes.get(node)
            if rec is None or rec.state == "healthy":
                raise ValueError(f"{node} is not quarantined")
            if rec.evacuated or recovery_says_dead:
                raise ValueError(
                    f"{node} was evacuated by the recovery plane; "
                    f"it cannot be released back — it must re-register "
                    f"as a fresh worker")
            ev = self._transition(node, rec, "healthy",
                                  [f"manual_release({actor})"])
            rec.manual = False
            rec.strikes = rec.clear = rec.canary_ok = rec.canary_fails = 0
            rec.slo_burn = 0
            rec.drain_recommended = False
            self._export_gauge_locked()
            pane = rec.pane()
        self._announce(ev)
        self._persist()
        return pane

    def _recovery_evacuated(self, node: str) -> bool:
        if self.recovery is None:
            return False
        try:
            return self.recovery.is_evacuated(node)
        except Exception:  # noqa: BLE001 — advisory cross-check
            return False

    def note_evacuated(self, node: str) -> None:
        """Recovery-plane hook: evacuation supersedes quarantine (the
        hard verdict wins; the node's record is retired so the scorer
        stops reasoning about a corpse)."""
        with self._lock:
            rec = self._nodes.get(node)
            if rec is None or rec.evacuated:
                return
            was = rec.state
            rec.evacuated = True
            self._export_gauge_locked()
        if was in ("quarantined", "rehabilitating"):
            try:
                _flight().record(
                    "health", f"{node}: {was} superseded by evacuation",
                    node=node, from_state=was, to_state="evacuated",
                    signals=["recovery.evacuate"])
            except Exception:  # noqa: BLE001
                logger.exception("health flight record failed")
            self._persist()

    # --- persistence (shard-takeover continuity) ---

    def _persist(self) -> None:
        if self.store is None:
            return
        with self._lock:
            state = {
                "version": 1,
                "nodes": {
                    n: {"state": r.state, "since": r.since,
                        "reason": r.reason, "manual": r.manual}
                    for n, r in self._nodes.items()
                    if r.state in ("quarantined", "rehabilitating")
                    and not r.evacuated},
            }
        try:
            self.store.save_health_state(state)
        except Exception as exc:  # noqa: BLE001 — best-effort; the
            # in-memory machine is authoritative for THIS master
            logger.warning("health state persist failed: %s", exc)

    def load(self) -> int:
        """Restore the quarantine set a previous master persisted (shard
        takeover / restart). Only quarantined/rehabilitating records are
        stored — healthy/suspect rebuild from live telemetry. Returns
        the number of nodes restored."""
        if self.store is None:
            return 0
        try:
            state = self.store.load_health_state()
        except Exception as exc:  # noqa: BLE001 — fail open
            logger.warning("health state load failed: %s", exc)
            return 0
        if not state or not isinstance(state.get("nodes"), dict):
            return 0
        restored = 0
        with self._lock:
            for node, saved in state["nodes"].items():
                if saved.get("state") not in ("quarantined",
                                              "rehabilitating"):
                    continue
                rec = self._nodes.setdefault(node, _NodeRecord())
                rec.state = saved["state"]
                rec.since = float(saved.get("since") or time.time())
                rec.reason = str(saved.get("reason") or "restored")
                rec.manual = bool(saved.get("manual"))
                restored += 1
            self._export_gauge_locked()
        if restored:
            logger.warning(
                "health: restored %d quarantined/rehabilitating node(s) "
                "from the store (takeover continuity)", restored)
        return restored

    # --- the pane ---

    def payload(self) -> dict:
        with self._lock:
            nodes = {n: r.pane() for n, r in self._nodes.items()}
            counts = {s: 0 for s in STATES}
            for r in self._nodes.values():
                if not r.evacuated:
                    counts[r.state] += 1
            total = self._last_pass.get("total") or len(nodes)
            return {
                "enabled": self.enabled,
                "nodes": nodes,
                "states": counts,
                "quarantine_budget": {
                    "fraction": float(self.cfg.health_quarantine_budget),
                    "max_nodes": max(1, int(
                        total * float(self.cfg.health_quarantine_budget))),
                    "used": counts["quarantined"],
                },
                "canary_active": self.canary_active,
                "last_pass": dict(self._last_pass),
            }


class CanaryProber:
    """Active gray-failure probe: a real synthetic mount -> verify ->
    unmount through the full worker path (grant, mknod, ledger) against
    a reserved canary pod, on the interval, for every decision-relevant
    node (suspect / quarantined / rehabilitating).

    The probe rides the breaker-aware client on purpose — it exercises
    exactly the path tenants pay — and the plane's scorer dedupes the
    breaker echo (see module docstring). A node without its canary pod
    scheduled answers PodNotFound; that is a *skip*, not a failure (the
    RUNBOOK covers deploying canary pods)."""

    def __init__(self, plane: HealthPlane, registry, client_factory,
                 cfg=None, probe=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.cfg = cfg
        self.plane = plane
        self.registry = registry
        self.client_factory = client_factory
        #: injectable probe(node, address) -> (ok: bool | None, detail);
        #: None = skip (no canary pod there). Tests/bench inject stubs.
        self.probe = probe or self._default_probe
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "CanaryProber":
        if not self.plane.enabled \
                or float(self.cfg.health_canary_interval_s) <= 0:
            return self
        self.plane.canary_active = True
        self._thread = threading.Thread(
            target=self._loop, name="canary-prober", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.plane.canary_active = False

    def _loop(self) -> None:
        while not self._stop.wait(float(self.cfg.health_canary_interval_s)):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("canary probe pass failed")

    def targets(self) -> list[str]:
        pane = self.plane.payload()["nodes"]
        return sorted(n for n, rec in pane.items()
                      if rec["state"] in ("suspect", "quarantined",
                                          "rehabilitating")
                      and not rec["evacuated"])

    def probe_once(self) -> int:
        """One probe pass over the decision-relevant set; returns probes
        actually driven (skips excluded). Probes fan out on the shared
        core (utils/fanout.py): the decision-relevant set is exactly the
        nodes most likely to burn the full probe deadline, so a serial
        pass degraded to minutes right when quarantine decisions needed
        the evidence fastest."""
        snapshot = dict(self.registry.registry_snapshot())
        work = []
        for node in self.targets():
            ip = snapshot.get(node)
            if ip is None:
                continue  # not registered: recovery's problem, not ours
            work.append((node, f"{ip}:{self.cfg.worker_port}"))
        if not work:
            return 0

        def _probe_one(item: tuple[str, str]):
            node, address = item
            try:
                ok, detail = self.probe(node, address)
            except Exception as exc:  # noqa: BLE001 — a probe that
                # cannot even dial IS the evidence
                ok, detail = False, f"{type(exc).__name__}: {exc}"
            return node, ok, detail

        from gpumounter_tpu.utils.fanout import get_core
        driven = 0
        for node, ok, detail in get_core(self.cfg).run(
                work, _probe_one, kind="canary-probe"):
            if ok is None:
                continue  # no canary pod on the node: skip, not fail
            driven += 1
            CANARY_PROBES.inc()
            if not ok:
                CANARY_FAILURES.inc()
            self.plane.record_canary(node, ok, detail)
        return driven

    def _default_probe(self, node: str,
                       address: str) -> tuple[bool | None, str]:
        from gpumounter_tpu.rpc import api
        pod = f"{self.cfg.health_canary_pod_prefix}{node}"
        ns = self.cfg.health_canary_namespace
        timeout = float(self.cfg.health_canary_timeout_s)
        failpoints.fire("health.canary", node=node)
        t0 = time.monotonic()
        with self.client_factory(address) as client:
            result = client.add_tpu(pod, ns, 1, timeout_s=timeout)
            if result == api.AddTPUResult.PodNotFound:
                return None, "canary pod not scheduled"
            if result != api.AddTPUResult.Success:
                return False, f"canary mount refused: {result.name}"
            try:
                probe, chips = client.probe_tpu(pod, ns, timeout_s=timeout)
                if probe != api.ProbeTPUResult.Success or not chips:
                    return False, "canary chip probe failed"
                if any(not c.healthy for c in chips):
                    return False, "canary chip unhealthy"
            finally:
                client.remove_tpu(pod, ns, [], force=True,
                                  remove_all=True, timeout_s=timeout)
        ms = (time.monotonic() - t0) * 1000.0
        if ms > timeout * 1000.0:
            return False, f"canary path took {ms:.0f}ms (> deadline)"
        return True, f"ok ({ms:.0f}ms)"
