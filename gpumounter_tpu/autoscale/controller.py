"""Autoscale controller: close the telemetry -> intent loop, gated.

Master-side, wired by MasterApp after the defragmenter. Every feedback
signal the last 18 PRs built feeds one decision loop:

  * the per-tenant throughput model (autoscale/model.py) says whether
    a tenant's current slice is saturated (`utilization` against the
    fitted batch->tokens/sec plateau) — and, critically, whether its
    telemetry is trustworthy at all (stale/sparse verdicts refuse),
  * queue depth from the same `/tenants` snapshots carries demand,
  * the capacity plane answers "where would a grow land": prefer hosts
    with an admissible free block NOW (warm chips first — grows are
    served from the warm pool at mount time), request a defrag pass on
    `admissible-after-defrag`, refuse on `infeasible`; quarantined
    hosts (health plane) are never counted as capacity,
  * tenant-SLO burn is a hard guardrail: while a tenant objective
    burns the controller refuses every decision (a scaler that moves
    capacity during a disruption incident is the incident's
    accelerant), and a degraded k8s API parks the pass at the next
    tenant boundary — decisions already journaled stand, nothing new
    fires,
  * hysteresis (signal streaks) + per-tenant cooldowns stop flapping,
    and shrinks never go below the tenant's declared min_chips floor.

Decisions actuate by writing elastic intents (elastic/intents.py) —
the reconciler owns convergence, including the graceful drain /
checkpoint-assisted migration machinery shrinks and heals ride. Every
decision is audited, trace-stamped and on the flight-recorder
timeline; the bounded metrics carry outcome/cause enums only (tenant
names ride the /autoscale JSON pane, never labels).

`enforce_gates` exists for the chaos harness's gates-disabled negative
control ONLY (the POLICY_ENGINE.enforce convention): with it off the
controller still RECORDS the true gate state in each decision, so
chaos invariant 21 can prove a decision fired through a closed gate.
"""

from __future__ import annotations

import copy
import threading
import time
from collections import deque

from gpumounter_tpu.autoscale.model import ThroughputModel
from gpumounter_tpu.config import get_config
from gpumounter_tpu.elastic.intents import Intent
from gpumounter_tpu.faults import failpoints
from gpumounter_tpu.k8s.errors import is_outage
from gpumounter_tpu.obs import trace
from gpumounter_tpu.obs.audit import AUDIT
from gpumounter_tpu.obs.capacity import host_capacity
from gpumounter_tpu.obs.flight import FLIGHT
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("autoscale")

#: tenant-facing SLO objectives whose burn refuses every decision
#: (never scale into a breach). slice-feasibility deliberately NOT
#: here: fragmentation burning is exactly when a grow may need to
#: request defrag — the feasibility gate handles it per decision.
GATING_OBJECTIVES = ("tenant-migration-downtime",
                     "tenant-disruption-free-minutes")

AUTOSCALE_DECISIONS = REGISTRY.counter(
    "tpumounter_autoscale_decisions_total",
    "Scale decisions fired, by outcome (grow|shrink)")
AUTOSCALE_SKIPS = REGISTRY.counter(
    "tpumounter_autoscale_skips_total",
    "Per-tenant evaluations that held, by bounded reason vocabulary")
AUTOSCALE_REFUSALS = REGISTRY.counter(
    "tpumounter_autoscale_refusals_total",
    "Whole passes refused/parked, by bounded cause vocabulary")
AUTOSCALE_PASSES = REGISTRY.counter(
    "tpumounter_autoscale_passes_total",
    "Evaluate passes completed (including no-decision passes)")
AUTOSCALE_PAUSED = REGISTRY.gauge(
    "tpumounter_autoscale_paused",
    "1 while the autoscaler is operator-paused")


class AutoscaleRefused(Exception):
    """Gate or pause refusal; maps to an HTTP status. The bounded
    `cause` vocabulary: slo-burn | api-degraded | paused | busy |
    stale-telemetry."""

    def __init__(self, message: str, cause: str, status: int = 409):
        super().__init__(message)
        self.cause = cause
        self.status = status


#: per-tenant skip reasons (bounded; AUTOSCALE_SKIPS label vocabulary)
SKIP_REASONS = ("stale-telemetry", "sparse-telemetry", "untracked",
                "cooldown", "hysteresis", "at-floor", "at-ceiling",
                "infeasible", "steady", "error")


class AutoscaleController:
    """One per master process; decision state in memory (a restarted
    master re-learns streaks/cooldowns within a few passes — the
    intents it wrote are the durable output, annotation-journaled like
    every other intent)."""

    def __init__(self, elastic, capacity, fleet, slo=None,
                 apihealth=None, health=None, defrag=None, cfg=None,
                 model=None, clock=None):
        self.cfg = cfg or get_config()
        self.elastic = elastic
        self.capacity = capacity
        self.fleet = fleet
        self.slo = slo
        self.apihealth = apihealth
        self.health = health
        #: optional DefragController: admissible-after-defrag grows
        #: request a plan instead of failing silently
        self.defrag = defrag
        self.clock = clock or time.time
        self.model = model or ThroughputModel(cfg=self.cfg,
                                              clock=self.clock)
        #: harness-only control; see module docstring
        self.enforce_gates = True
        self._lock = OrderedLock("autoscale.state")
        self._paused = threading.Event()
        #: tenant -> {"grow": streak, "shrink": streak}
        self._streaks: dict[str, dict] = {}
        #: tenant -> last grow/shrink decision time (cooldowns)
        self._cooldowns: dict[str, float] = {}
        self._history: deque[dict] = deque(maxlen=32)
        self._last_pass: dict | None = None
        self._pass_mu = OrderedLock("autoscale.pass")
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None

    # --- gates (the defrag controller's fail-closed shape) ---

    def _gate_state(self) -> dict:
        burning = []
        if self.slo is not None:
            try:
                evaluation = self.slo.evaluate()
            except Exception as exc:  # noqa: BLE001 — a broken SLO
                # engine reads as burning: fail closed, autoscaling is
                # an optimization, never a liveness path
                logger.warning("slo evaluation for autoscale gate "
                               "failed: %s", exc)
                burning = ["slo-engine-error"]
            else:
                threshold = float(evaluation.get("burn_threshold", 2.0))
                for objective in evaluation.get("objectives", []):
                    if objective.get("name") not in GATING_OBJECTIVES:
                        continue
                    if objective.get("breached") or \
                            float(objective.get("burn_fast", 0.0)) \
                            >= threshold:
                        burning.append(objective["name"])
        api_ok = self.apihealth is None or self.apihealth.ok()
        return {"api_ok": api_ok,
                "api_state": (self.apihealth.state()
                              if self.apihealth is not None else "ok"),
                "slo_burning": burning,
                "paused": self._paused.is_set()}

    def _check_gates(self, action: str) -> dict:
        gates = self._gate_state()
        if not self.enforce_gates:
            return gates
        if gates["paused"]:
            self._refuse(action, "paused",
                         "autoscaler is operator-paused; POST "
                         "/autoscale/resume to re-enable", 409)
        if not gates["api_ok"]:
            self._refuse(action, "api-degraded",
                         f"k8s api is {gates['api_state']}; the "
                         f"autoscaler parks until it heals", 503)
        if gates["slo_burning"]:
            self._refuse(action, "slo-burn",
                         f"SLO burning: {', '.join(gates['slo_burning'])}"
                         f"; refusing to scale into a breach", 503)
        return gates

    def _refuse(self, action: str, cause: str, message: str,
                status: int = 409) -> None:
        AUTOSCALE_REFUSALS.inc(outcome=cause)
        AUDIT.record(f"autoscale.{action}", actor="autoscale-controller",
                     outcome=f"refused: {cause}", cause=cause,
                     detail=message)
        raise AutoscaleRefused(message, cause, status)

    # --- feasibility (where would a grow land) ---

    def _grow_feasibility(self, need: int, nodes: dict,
                          claims: list[int] | None = None) -> dict:
        """Can the fleet place `need` more chips as one ICI block on a
        single non-quarantined host? Mirrors the capacity plane's
        verdict vocabulary so operators read one language everywhere.
        Warm chips count toward after-defrag capacity only — warm
        holders are reclaimable bookings, not free blocks.

        claims: chip counts already granted to earlier tenants in THIS
        pass. The snapshot doesn't see them (actuation is an intent
        write, not an instant mount), so they are simulated here —
        best-fit against the admissible hosts — before judging `need`.
        This is what makes evaluation order an allocation order under
        contention: a high-priority tenant's grow consumes the block a
        lower-priority tenant would otherwise double-book."""
        excluded = frozenset()
        if self.health is not None:
            try:
                excluded = self.health.excluded_hosts()
            except Exception:  # noqa: BLE001 — fail-open exclusion,
                # exactly like every other excluded_hosts consumer
                excluded = frozenset()
        hosts = []
        warm_ready = 0
        for node, entry in nodes.items():
            if node in excluded:
                continue
            cap = host_capacity((entry or {}).get("capacity"))
            if cap.get("capacity_unknown"):
                continue
            warm_ready += int(cap.get("warm_ready", 0))
            hosts.append({"largest_block": int(cap["largest_block"]),
                          "loose": int(cap["free"]) + int(cap["warm"])})
        for claim in claims or ():
            # best-fit: the smallest block that holds the claim, so big
            # blocks survive for big later grows
            fit = min((h for h in hosts
                       if h["largest_block"] >= claim),
                      key=lambda h: h["largest_block"], default=None)
            if fit is None:
                fit = min((h for h in hosts if h["loose"] >= claim),
                          key=lambda h: h["loose"], default=None)
            if fit is not None:
                fit["largest_block"] = max(
                    0, fit["largest_block"] - claim)
                fit["loose"] -= claim
        admissible_now = 0
        after_defrag = 0
        for h in hosts:
            if h["largest_block"] >= need:
                admissible_now += 1
            elif h["loose"] >= need:
                after_defrag += 1
        if admissible_now:
            verdict = "admissible"
        elif after_defrag:
            verdict = "admissible-after-defrag"
        else:
            verdict = "infeasible"
        return {"verdict": verdict, "chips": need,
                "hosts_admissible_now": admissible_now,
                "hosts_after_defrag": after_defrag,
                "warm_ready": warm_ready,
                "excluded_hosts": len(excluded)}

    def _request_defrag(self, tenant: str, need: int) -> None:
        """An admissible-after-defrag grow cannot land yet — hand the
        contiguity problem to the defragmenter (which runs under its
        own gates/budgets) and record the handoff. Best-effort: a
        refused or absent defragmenter leaves the grow deferred, and
        the next pass re-evaluates."""
        FLIGHT.record("marker",
                      f"autoscale: grow of {need} chip(s) for {tenant} "
                      f"needs defrag; requesting a plan")
        if self.defrag is None:
            return
        try:
            plan = self.defrag.plan()
            if plan.get("moves"):
                self.defrag.run(plan["id"])
        except Exception as exc:  # noqa: BLE001 — the defragmenter
            # refusing (its own gates) or failing must not fail the
            # autoscale pass; the deferral is already recorded
            logger.info("defrag request for %s deferred: %s", tenant,
                        exc)

    # --- the decision pass ---

    def evaluate_once(self) -> dict:
        """One full pass: fold fresh telemetry into the model, then
        evaluate every tenant that has an elastic intent. Raises
        AutoscaleRefused when a gate is closed at the top; parks
        mid-pass (status parked-api / parked-slo) when a gate closes
        between tenants — the journal-boundary contract."""
        with self._pass_mu:
            with trace.span("autoscale.pass"):
                return self._evaluate_traced()

    def _evaluate_traced(self) -> dict:
        now = self.clock()
        record = {"at": now, "status": "running", "decisions": [],
                  "considered": 0,
                  "trace_id": trace.current_trace_id()}
        gates = self._check_gates("pass")
        failpoints.fire("autoscale.pass")
        try:
            rollup = self.fleet.payload(
                max_age_s=float(self.cfg.autoscale_stale_s))
        except Exception as exc:  # noqa: BLE001 — no fleet view means
            # no trustworthy telemetry OR capacity: refuse like stale
            self._refuse(
                "pass", "stale-telemetry",
                f"fleet collection failed "
                f"({'api outage' if is_outage(exc) else exc}); "
                f"refusing to scale blind", 503)
        nodes = rollup.get("nodes") or {}
        self.model.observe_nodes(nodes)
        try:
            intents = list(self.elastic.store.list())
        except Exception as exc:  # noqa: BLE001 — intent listing
            # rides the k8s API; treat like the fleet failure above
            self._refuse(
                "pass", "api-degraded",
                f"intent listing failed "
                f"({'api outage' if is_outage(exc) else exc})", 503)
        from gpumounter_tpu.obs.fleet import merge_tenants
        snapshots = merge_tenants(nodes)
        # Priority classes under contention: higher tpumounter.io/priority
        # tenants are evaluated (and so claim spare capacity) first; the
        # default class (priority 0) keeps today's stable alphabetical
        # order. Capacity gates close mid-pass, so evaluation order IS
        # allocation order when the fleet cannot fit every grow.
        pass_claims: list[int] = []
        for namespace, pod_name, intent in sorted(
                intents,
                key=lambda t: (-t[2].priority, t[0], t[1])):
            # journal boundary: gates re-checked between tenants; a
            # mid-pass degradation parks the REST of the pass, never
            # unwinds decisions already journaled
            gates = self._gate_state()
            if self.enforce_gates and not gates["api_ok"]:
                record["status"] = "parked-api"
                record["parked"] = gates["api_state"]
                AUTOSCALE_REFUSALS.inc(outcome="api-degraded")
                break
            if self.enforce_gates and gates["slo_burning"]:
                record["status"] = "parked-slo"
                record["parked"] = gates["slo_burning"]
                AUTOSCALE_REFUSALS.inc(outcome="slo-burn")
                break
            if self.enforce_gates and gates["paused"]:
                record["status"] = "paused"
                AUTOSCALE_REFUSALS.inc(outcome="paused")
                break
            record["considered"] += 1
            decision = self._decide(namespace, pod_name, intent,
                                    snapshots, nodes, gates, now,
                                    pass_claims)
            record["decisions"].append(decision)
            if decision["action"] == "grow":
                pass_claims.append(decision["to_chips"]
                                   - decision["from_chips"])
        if record["status"] == "running":
            record["status"] = "completed"
        AUTOSCALE_PASSES.inc()
        fired = [d for d in record["decisions"]
                 if d["action"] in ("grow", "shrink")]
        if fired:
            AUDIT.record(
                "autoscale.pass", actor="autoscale-controller",
                outcome=f"{record['status']}: {len(fired)} decision(s) "
                        f"over {record['considered']} tenant(s)",
                decisions=len(fired), considered=record["considered"])
        with self._lock:
            self._last_pass = record
            self._history.append(copy.deepcopy(record))
        return copy.deepcopy(record)

    def _decide(self, namespace: str, pod_name: str, intent: Intent,
                snapshots: dict, nodes: dict, gates: dict,
                now: float, pass_claims: list[int] | None = None) -> dict:
        tenant = f"{namespace}/{pod_name}"
        decision = {"at": now, "tenant": tenant,
                    "namespace": namespace, "pod": pod_name,
                    "from_chips": intent.desired_chips,
                    "action": "hold", "reason": "steady",
                    "gates": gates,
                    "trace_id": trace.current_trace_id()}

        def hold(reason: str) -> dict:
            decision["reason"] = reason
            AUTOSCALE_SKIPS.inc(outcome=reason)
            self._streaks.pop(tenant, None)
            return decision

        fit = self.model.fit(tenant, now=now)
        decision["fit"] = fit
        if fit["verdict"] != "ok":
            # refuse, don't thrash: no decision on untrusted telemetry
            return hold({"stale": "stale-telemetry",
                         "sparse": "sparse-telemetry",
                         "untracked": "untracked"}.get(
                             fit["verdict"], "error"))
        snap = snapshots.get(tenant) or {}
        queue = float(snap.get("queue_depth") or 0.0)
        util = float(fit.get("utilization", 0.0))
        decision["queue_depth"] = queue
        decision["utilization"] = util
        wants_grow = (queue >= float(self.cfg.autoscale_queue_grow)
                      and util >= float(self.cfg.autoscale_util_grow))
        wants_shrink = (queue <= float(self.cfg.autoscale_queue_shrink)
                        and util
                        <= float(self.cfg.autoscale_util_shrink))
        if not wants_grow and not wants_shrink:
            return hold("steady")
        direction = "grow" if wants_grow else "shrink"
        streaks = self._streaks.setdefault(
            tenant, {"grow": 0, "shrink": 0})
        # a flipped signal resets the opposite streak: hysteresis means
        # N CONSECUTIVE passes agreeing, not N passes ever
        streaks["grow" if wants_shrink else "shrink"] = 0
        streaks[direction] += 1
        decision["streak"] = streaks[direction]
        if streaks[direction] < int(self.cfg.autoscale_hysteresis):
            decision["reason"] = "hysteresis"
            AUTOSCALE_SKIPS.inc(outcome="hysteresis")
            return decision
        last = self._cooldowns.get(tenant)
        if last is not None and \
                now - last < float(self.cfg.autoscale_cooldown_s):
            decision["reason"] = "cooldown"
            decision["cooldown_remaining_s"] = round(
                float(self.cfg.autoscale_cooldown_s) - (now - last), 1)
            AUTOSCALE_SKIPS.inc(outcome="cooldown")
            return decision
        step = max(1, int(self.cfg.autoscale_max_step))
        if direction == "grow":
            ceiling = int(self.cfg.max_tpu_per_request)
            target = min(intent.desired_chips + step, ceiling)
            if target <= intent.desired_chips:
                return hold("at-ceiling")
            feas = self._grow_feasibility(
                target - intent.desired_chips, nodes,
                claims=pass_claims)
            decision["feasibility"] = feas
            if feas["verdict"] == "infeasible":
                return hold("infeasible")
            if feas["verdict"] == "admissible-after-defrag":
                # defer the grow; the defragmenter works the contiguity
                # problem and the next pass re-evaluates against the
                # recovered fleet
                self._request_defrag(tenant,
                                     target - intent.desired_chips)
                decision["reason"] = "infeasible"
                decision["deferred"] = "requested-defrag"
                AUTOSCALE_SKIPS.inc(outcome="infeasible")
                return decision
        else:
            floor = max(1, intent.min_chips)
            target = max(intent.desired_chips - step, floor)
            if target >= intent.desired_chips:
                return hold("at-floor")
        return self._actuate(decision, namespace, pod_name, intent,
                             target, direction, now)

    def _actuate(self, decision: dict, namespace: str, pod_name: str,
                 intent: Intent, target: int, direction: str,
                 now: float) -> dict:
        tenant = decision["tenant"]
        try:
            self.elastic.store.put(
                namespace, pod_name,
                Intent(desired_chips=target,
                       min_chips=intent.min_chips,
                       priority=intent.priority))
            self.elastic.enqueue(namespace, pod_name)
        except Exception as exc:  # noqa: BLE001 — actuation boundary:
            # a failed intent write is a recorded non-decision, and the
            # streak survives so the next pass retries
            decision["action"] = "hold"
            decision["reason"] = "error"
            decision["error"] = str(exc)
            AUTOSCALE_SKIPS.inc(outcome="error")
            logger.warning("autoscale %s of %s failed to write intent: "
                           "%s", direction, tenant, exc)
            return decision
        decision["action"] = direction
        decision["to_chips"] = target
        decision["reason"] = ("saturated-queue" if direction == "grow"
                              else "idle-capacity")
        self._cooldowns[tenant] = now
        self._streaks.pop(tenant, None)
        AUTOSCALE_DECISIONS.inc(outcome=direction)
        summary = (f"autoscale {direction} {tenant}: "
                   f"{decision['from_chips']} -> {target} chip(s) "
                   f"(queue {decision['queue_depth']:.0f}, "
                   f"utilization {decision['utilization']:.2f})")
        AUDIT.record("autoscale.decision", actor="autoscale-controller",
                     outcome=f"{direction}: {decision['from_chips']} "
                             f"-> {target}",
                     namespace=namespace, pod=pod_name,
                     action=direction,
                     from_chips=decision["from_chips"],
                     to_chips=target,
                     queue_depth=decision["queue_depth"],
                     utilization=decision["utilization"],
                     trace_id=decision["trace_id"])
        FLIGHT.record("marker", summary,
                      trace_id=decision["trace_id"] or "")
        logger.info("%s", summary)
        return decision

    # --- pause / resume ---

    def pause(self, actor: str = "operator") -> dict:
        """Stop deciding (idempotent). In-flight passes park at the
        next tenant boundary; reads keep working."""
        self._paused.set()
        AUTOSCALE_PAUSED.set(1.0)
        AUDIT.record("autoscale.pause", actor=actor, outcome="paused")
        FLIGHT.record("marker", f"autoscale paused by {actor}")
        return self.payload()

    def resume(self, actor: str = "operator") -> dict:
        self._paused.clear()
        AUTOSCALE_PAUSED.set(0.0)
        AUDIT.record("autoscale.resume", actor=actor, outcome="resumed")
        FLIGHT.record("marker", f"autoscale resumed by {actor}")
        return self.payload()

    @property
    def paused(self) -> bool:
        return self._paused.is_set()

    # --- surfaces ---

    def payload(self) -> dict:
        """The GET /autoscale response: gate verdicts, the model's
        per-tenant fits, the last pass and recent decision history."""
        gates = self._gate_state()
        now = self.clock()
        with self._lock:
            last = copy.deepcopy(self._last_pass)
            history = [copy.deepcopy(r) for r in self._history]
            cooldowns = {
                t: round(float(self.cfg.autoscale_cooldown_s)
                         - (now - at), 1)
                for t, at in self._cooldowns.items()
                if now - at < float(self.cfg.autoscale_cooldown_s)}
        decisions = [d for r in history for d in r["decisions"]
                     if d["action"] in ("grow", "shrink")]
        return {
            "at": round(now, 3),
            "enabled": bool(self.cfg.autoscale_enabled),
            "paused": gates["paused"],
            "gates": gates,
            "model": self.model.payload(now=now),
            "last_pass": last,
            "decisions": decisions[-16:],
            "cooldowns": cooldowns,
        }

    # --- background loop (opt-in via autoscale_enabled) ---

    def start(self) -> None:
        if self._loop_thread is not None:
            return
        self._stop.clear()
        self._loop_thread = threading.Thread(
            target=self._loop, name="autoscale-loop", daemon=True)
        self._loop_thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._loop_thread
        if thread is not None:
            thread.join(timeout=5.0)
        self._loop_thread = None

    def _loop(self) -> None:
        while not self._stop.wait(float(self.cfg.autoscale_interval_s)):
            try:
                self.evaluate_once()
            except AutoscaleRefused as exc:
                logger.info("autoscale pass parked: %s (%s)", exc,
                            exc.cause)
            except Exception as exc:  # noqa: BLE001 — the loop is the
                # scaling heartbeat; one bad pass must not kill it
                logger.exception("autoscale pass failed: %s", exc)
