"""Per-tenant batch-size -> tokens/sec throughput model.

The autoscaler's decisions are only as good as its idea of what a
tenant's current chips can actually deliver. Naive queue-threshold
scaling grows a tenant that is queue-deep because its batch is tiny
(more chips would idle) and shrinks one that is briefly quiet at full
saturation (the next wave hits a half-sized slice). The fix, per the
batch-size characterization literature (PAPERS.md), is to scale along
a *measured* saturating curve:

    rate(b) = r_max * b / (b + b_half)

fit online from the tenant's own `/tenants` step telemetry. Tenant
snapshots (jaxside/telemetry.py) carry cumulative step and token
counters, not an explicit batch size, so each observation is a DELTA
between consecutive snapshots: batch = d_tokens / d_steps (tokens per
step — the per-step work size the serving stack actually ran), paired
with the published tokens_per_s for that window.

The fit is the linearized least squares of the Michaelis-Menten form
(1/r against 1/b): stdlib-only, O(history) per fit, robust enough for
the monotone saturating shapes step servers produce. What matters more
than fit quality is the refusal discipline: a tenant with fewer than
``autoscale_min_samples`` observations is `sparse`, one whose newest
sample is older than ``autoscale_stale_s`` is `stale`, and the
controller acts on neither — the capacity plane's "refuse, don't
thrash" contract applied to telemetry (docs/FAQ.md).

History is bounded per tenant (``autoscale_history`` deque) and the
tenant table is bounded (``autoscale_max_tenants``, the obs/tenants.py
256-tenant convention): a churny namespace cannot grow this model's
memory, and nothing here ever becomes a metric label.
"""

from __future__ import annotations

import time
from collections import deque

from gpumounter_tpu.config import get_config
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger

logger = get_logger("autoscale.model")

#: fit verdict vocabulary (bounded; FAQ documents each)
VERDICTS = ("ok", "sparse", "stale", "untracked")


def fit_curve(samples: list[tuple[float, float]]) -> dict | None:
    """Least-squares fit of rate = r_max * b / (b + b_half) over
    (batch, rate) pairs via the double-reciprocal linearization
    1/r = (b_half/r_max) * (1/b) + 1/r_max. Returns {r_max, b_half,
    rmse} or None when the inputs are degenerate (all-equal batches
    carry no curvature — fall back to the mean-rate plateau)."""
    pts = [(b, r) for b, r in samples if b > 0 and r > 0]
    if len(pts) < 2:
        return None
    xs = [1.0 / b for b, _ in pts]
    ys = [1.0 / r for _, r in pts]
    n = float(len(pts))
    mean_x = sum(xs) / n
    mean_y = sum(ys) / n
    var_x = sum((x - mean_x) ** 2 for x in xs)
    if var_x <= 1e-12:
        # One distinct batch size: no slope is identifiable. Treat the
        # observed mean rate as the plateau (b_half=0 -> rate==r_max
        # at any batch) so utilization still reads sanely.
        mean_rate = sum(r for _, r in pts) / n
        return {"r_max": mean_rate, "b_half": 0.0, "rmse": 0.0,
                "plateau_only": True}
    slope = sum((x - mean_x) * (y - mean_y)
                for x, y in zip(xs, ys)) / var_x
    intercept = mean_y - slope * mean_x
    if intercept <= 1e-12:
        # A non-positive 1/r_max means the linearization broke on this
        # window (heavy noise); report the plateau fallback instead of
        # an infinite capacity the controller would scale against.
        mean_rate = sum(r for _, r in pts) / n
        return {"r_max": mean_rate, "b_half": 0.0, "rmse": 0.0,
                "plateau_only": True}
    r_max = 1.0 / intercept
    b_half = max(0.0, slope * r_max)
    err = 0.0
    for b, r in pts:
        pred = r_max * b / (b + b_half) if (b + b_half) > 0 else 0.0
        err += (pred - r) ** 2
    return {"r_max": r_max, "b_half": b_half,
            "rmse": (err / n) ** 0.5, "plateau_only": False}


def predict(fit: dict, batch: float) -> float:
    """Modeled tokens/sec at a batch size, from a fit_curve() result."""
    b_half = float(fit.get("b_half", 0.0))
    r_max = float(fit.get("r_max", 0.0))
    if batch <= 0 or (batch + b_half) <= 0:
        return 0.0
    return r_max * batch / (batch + b_half)


class ThroughputModel:
    """Bounded online store of per-tenant throughput observations plus
    the fit/verdict surface the controller consumes. One per master
    process; all state in memory (the model re-learns from live
    telemetry within a few scrapes of a restart — deliberately not
    durable, matching the defrag planner's cheap-to-recompute stance).
    """

    def __init__(self, cfg=None, clock=None):
        self.cfg = cfg or get_config()
        #: injectable clock (the diurnal bench drives simulated time)
        self.clock = clock or time.time
        self._lock = OrderedLock("autoscale.model")
        #: tenant -> deque[(at, batch, tokens_per_s)]
        self._samples: dict[str, deque] = {}
        #: tenant -> last cumulative snapshot used for the delta
        self._last: dict[str, dict] = {}
        #: tenants refused by the table bound (a count, not names:
        #: unbounded names stay out of every payload and label)
        self.overflow_dropped = 0

    # --- ingestion ---

    def observe(self, tenant: str, snapshot: dict) -> tuple | None:
        """Fold one /tenants snapshot in. Returns the derived
        (at, batch, tokens_per_s) sample, or None when the snapshot
        yields no new delta (first sighting, no step progress, counter
        reset, or tenant-table overflow)."""
        steps = (snapshot.get("steps") or {})
        count = float(steps.get("count") or 0.0)
        tokens = float(snapshot.get("tokens_total") or 0.0)
        at = float(snapshot.get("at") or 0.0)
        rate = float(snapshot.get("tokens_per_s") or 0.0)
        with self._lock:
            prev = self._last.get(tenant)
            if prev is None and tenant not in self._samples:
                limit = int(self.cfg.autoscale_max_tenants)
                if len(self._samples) >= limit:
                    self.overflow_dropped += 1
                    return None
                self._samples[tenant] = deque(
                    maxlen=max(2, int(self.cfg.autoscale_history)))
            self._last[tenant] = {"count": count, "tokens": tokens,
                                  "at": at}
            if prev is None:
                return None
            d_steps = count - prev["count"]
            d_tokens = tokens - prev["tokens"]
            if d_steps <= 0 or d_tokens <= 0 or at <= prev["at"]:
                # no progress, or a restarted tenant reset its
                # cumulative counters — re-baseline, never extrapolate
                return None
            batch = d_tokens / d_steps
            if rate <= 0.0:
                rate = d_tokens / max(1e-9, at - prev["at"])
            sample = (at, batch, rate)
            self._samples[tenant].append(sample)
            return sample

    def observe_nodes(self, nodes: dict) -> int:
        """Fleet-collector observer hook (same contract as the capacity
        and health planes): fold every tenant snapshot from a fresh
        node map. Returns samples derived. Never raises."""
        derived = 0
        try:
            from gpumounter_tpu.obs.fleet import merge_tenants
            for tenant, snap in merge_tenants(nodes).items():
                if self.observe(tenant, snap) is not None:
                    derived += 1
        except Exception:  # noqa: BLE001 — observer contract: the
            # model is advisory; its bugs must not fail telemetry
            logger.exception("throughput observation failed")
        return derived

    def forget(self, tenant: str) -> None:
        with self._lock:
            self._samples.pop(tenant, None)
            self._last.pop(tenant, None)

    # --- fitting ---

    def fit(self, tenant: str, now: float | None = None) -> dict:
        """The controller's question: what does this tenant's curve
        look like, and may I act on it? Always returns a dict with a
        `verdict` from VERDICTS; curve parameters only when "ok"."""
        now = self.clock() if now is None else now
        with self._lock:
            samples = list(self._samples.get(tenant) or ())
        out: dict = {"tenant": tenant, "samples": len(samples)}
        if tenant not in self._samples:
            out["verdict"] = "untracked"
            return out
        if len(samples) < int(self.cfg.autoscale_min_samples):
            out["verdict"] = "sparse"
            return out
        newest = max(at for at, _, _ in samples)
        age = now - newest
        out["newest_age_s"] = round(age, 3)
        if age > float(self.cfg.autoscale_stale_s):
            out["verdict"] = "stale"
            return out
        curve = fit_curve([(b, r) for _, b, r in samples])
        if curve is None:
            out["verdict"] = "sparse"
            return out
        out["verdict"] = "ok"
        out.update(r_max=round(curve["r_max"], 3),
                   b_half=round(curve["b_half"], 3),
                   rmse=round(curve["rmse"], 3),
                   plateau_only=curve["plateau_only"])
        last_at, last_batch, last_rate = samples[-1]
        out["last_batch"] = round(last_batch, 3)
        out["last_rate"] = round(last_rate, 3)
        # Utilization: observed rate against the modeled plateau. At
        # 1.0 the tenant is extracting everything its current slice
        # can give — more queue means more chips, not bigger batches.
        if curve["r_max"] > 0:
            out["utilization"] = round(
                min(2.0, last_rate / curve["r_max"]), 3)
        else:
            out["utilization"] = 0.0
        return out

    # --- surfaces ---

    def payload(self, now: float | None = None) -> dict:
        """The model half of GET /autoscale: per-tenant fit summaries
        (bounded by the tenant cap) + the overflow count."""
        with self._lock:
            tenants = sorted(self._samples)
        return {
            "tenants": {t: self.fit(t, now=now) for t in tenants},
            "tracked": len(tenants),
            "overflow_dropped": self.overflow_dropped,
        }
