"""Closed-loop autoscaler: tenant telemetry in, elastic intents out.

Two halves (ISSUE 19): the per-tenant batch->tokens/sec throughput
model (model.py — bounded history, stale/sparse refusal verdicts) and
the gated decision controller (controller.py — SLO/ApiHealth/
quarantine guardrails, capacity-feasibility sourcing, hysteresis and
cooldowns, audited + trace-stamped decisions). Surfaces: GET
/autoscale, POST /autoscale/{pause,resume}, `tpumounter autoscale`.
"""

from gpumounter_tpu.autoscale.controller import (
    GATING_OBJECTIVES,
    SKIP_REASONS,
    AutoscaleController,
    AutoscaleRefused,
)
from gpumounter_tpu.autoscale.model import (
    VERDICTS,
    ThroughputModel,
    fit_curve,
    predict,
)

__all__ = [
    "AutoscaleController",
    "AutoscaleRefused",
    "GATING_OBJECTIVES",
    "SKIP_REASONS",
    "ThroughputModel",
    "VERDICTS",
    "fit_curve",
    "predict",
]
