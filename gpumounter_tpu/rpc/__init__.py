from gpumounter_tpu.rpc import api, wire

__all__ = ["api", "wire"]
