"""RPC resilience primitives: typed errors, bounded retry, circuit breaker.

The reference master dials each worker with no deadline discipline beyond
a single huge timeout and no failure memory at all: one wedged worker
node makes every request that routes to it hang for the full timeout,
serially, forever. Here every master→worker call gets

  * a per-method deadline (config-driven, overridable per call),
  * a capped-exponential bounded retry for retriable transport codes
    (safe because AddTPU/RemoveTPU carry idempotency keys and
    Probe/QuiesceStatus are read-only),
  * a per-worker circuit breaker: after `failure_threshold` consecutive
    transport failures the worker's WorkerRegistry entry is degraded —
    calls fail fast with BreakerOpenError, the master's HTTP routes turn
    that into 503 + Retry-After, and the elastic reconciler's workqueue
    backoff absorbs it. After `reset_s` one half-open probe is let
    through; success closes the breaker, failure re-opens it.

Stdlib-only; grpc types are touched only by the client (lazy-grpc policy).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("rpc.resilience")

BREAKER_OPEN = REGISTRY.gauge(
    "tpumounter_worker_breaker_open",
    "1 while the named worker's circuit breaker is open (degraded)")
BREAKER_TRIPS = REGISTRY.counter(
    "tpumounter_worker_breaker_trips_total",
    "Circuit-breaker open transitions by worker")
RPC_RETRIES = REGISTRY.counter(
    "tpumounter_rpc_retries_total",
    "Worker RPC attempts retried after a retriable transport failure")


class RpcCallError(RuntimeError):
    """Base for typed master→worker RPC failures.

    `code` is the gRPC status name ("DEADLINE_EXCEEDED", "UNAVAILABLE",
    ...) or a synthetic one ("BREAKER_OPEN", "INJECTED")."""

    def __init__(self, message: str, code: str = "UNKNOWN",
                 address: str = "", method: str = ""):
        super().__init__(message)
        self.code = code
        self.address = address
        self.method = method


class DeadlineExceededError(RpcCallError):
    """The per-call deadline elapsed (grpc DEADLINE_EXCEEDED)."""

    def __init__(self, message: str, address: str = "", method: str = ""):
        super().__init__(message, "DEADLINE_EXCEEDED", address, method)


class WorkerUnavailableError(RpcCallError):
    """Transport-level failure: connection refused/dropped (UNAVAILABLE)."""

    def __init__(self, message: str, address: str = "", method: str = ""):
        super().__init__(message, "UNAVAILABLE", address, method)


class BreakerOpenError(RpcCallError):
    """The worker's circuit breaker is open; fail fast, retry later."""

    def __init__(self, message: str, retry_after_s: float,
                 address: str = "", method: str = ""):
        super().__init__(message, "BREAKER_OPEN", address, method)
        self.retry_after_s = retry_after_s


class FencedError(RpcCallError):
    """The worker rejected a stale-epoch write (epoch fencing).

    This caller's view of node ownership is behind: another master
    replica has taken over the node's shard since this epoch was read.
    NEVER retried by the transport layer — the correct response is to
    refresh shard routing (the lease table) and let the current owner
    drive the mutation, not to re-send the stale write."""

    def __init__(self, message: str, address: str = "", method: str = ""):
        super().__init__(message, "FENCED", address, method)


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff between bounded attempts.

    `max_attempts` counts the first try: max_attempts=3 means at most two
    retries. Worst-case wall time per logical call is therefore
    max_attempts * deadline + sum(delays) — bounded by construction."""

    max_attempts: int = 3
    base_s: float = 0.1
    factor: float = 2.0
    cap_s: float = 2.0

    def delay_for(self, attempt: int) -> float:
        """Sleep before retry number `attempt` (1-based)."""
        if attempt <= 0:
            return 0.0
        return min(self.base_s * self.factor ** (attempt - 1), self.cap_s)


class CircuitBreaker:
    """Per-key (worker address) consecutive-failure breaker.

    States: closed (normal) → open after `failure_threshold` consecutive
    transport failures → half-open after `reset_s` (exactly one probe
    call allowed through) → closed on probe success / open on failure.
    """

    def __init__(self, failure_threshold: int = 5, reset_s: float = 30.0):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_s = reset_s
        self._lock = threading.Lock()
        #: key -> [consecutive_failures, opened_at or None, probe_in_flight]
        self._entries: dict[str, list] = {}
        #: called (outside the lock) with the key on every closed→open
        #: and half-open→open transition. The WorkerRegistry wires this
        #: to ChannelPool.invalidate so a degraded worker's cached
        #: channel is dropped — when the worker recovers, the half-open
        #: probe gets a fresh dial instead of a wedged connection.
        self.on_open = None

    # --- views (non-mutating; the master's route pre-check) ---

    def state(self, key: str) -> str:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry[1] is None:
                return "closed"
            if time.monotonic() - entry[1] >= self.reset_s:
                return "half-open"
            return "open"

    def retry_after(self, key: str) -> float | None:
        """Seconds until a retry is worth making, or None when calls may
        proceed. Pure read: does NOT consume the half-open probe slot —
        callers that actually dial must still pass allow()."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry[1] is None:
                return None
            remaining = self.reset_s - (time.monotonic() - entry[1])
            return max(0.0, remaining) if remaining > 0 else None

    def snapshot(self) -> dict[str, str]:
        with self._lock:
            keys = list(self._entries)
        return {k: self.state(k) for k in keys}

    def prune(self, active_keys) -> None:
        """Drop state for workers that no longer exist (registry churn):
        without this, a replaced worker's open breaker pins its degraded
        gauge forever and _entries grows with every churned address."""
        active = set(active_keys)
        with self._lock:
            stale = [k for k in self._entries if k not in active]
            removed = [(k, self._entries.pop(k)) for k in stale]
        for key, entry in removed:
            if entry[1] is not None:  # was open/half-open: clear the alert
                logger.info("circuit breaker for %s pruned (worker gone)",
                            key)
                BREAKER_OPEN.set(0.0, worker=key)

    # --- the dialing contract ---

    def allow(self, key: str) -> float | None:
        """None = proceed (and in half-open, this call claims the single
        probe slot); a float = open, retry after that many seconds."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry[1] is None:
                return None
            elapsed = time.monotonic() - entry[1]
            if elapsed < self.reset_s:
                return self.reset_s - elapsed
            if entry[2]:  # half-open, probe already in flight
                return 1.0
            entry[2] = True
            return None

    def record_success(self, key: str) -> None:
        with self._lock:
            # Steady state (no entry): nothing to clear, and no gauge
            # write — healthy workers must not pay a metric mutation per
            # RPC or grow a labeled series each.
            if key not in self._entries:
                return
            entry = self._entries.pop(key)
            was_open = entry[1] is not None
        if was_open:
            logger.info("circuit breaker for %s closed (probe ok)", key)
            BREAKER_OPEN.set(0.0, worker=key)

    def record_failure(self, key: str) -> None:
        tripped = False
        reopened = False
        with self._lock:
            entry = self._entries.setdefault(key, [0, None, False])
            entry[0] += 1
            if entry[1] is not None:
                # open/half-open: failure (the probe, or a racer) re-opens
                # and restarts the reset clock.
                reopened = entry[2]  # a half-open probe just failed
                entry[1] = time.monotonic()
                entry[2] = False
            elif entry[0] >= self.failure_threshold:
                entry[1] = time.monotonic()
                entry[2] = False
                tripped = True
        if tripped:
            logger.error(
                "circuit breaker for %s OPEN after %d consecutive "
                "failures; degrading for %.0fs", key,
                self.failure_threshold, self.reset_s)
            BREAKER_TRIPS.inc(worker=key)
            BREAKER_OPEN.set(1.0, worker=key)
        if tripped or reopened:
            on_open = self.on_open
            if on_open is not None:
                try:
                    on_open(key)
                except Exception as exc:  # noqa: BLE001 — advisory hook
                    logger.warning("breaker on_open hook failed: %s", exc)
