"""Master-replica HTTP client: failover across replicas + shard redirects.

With sharded masters (master/shard.py) a client holds a LIST of replica
URLs, any of which can serve any request: a replica that does not own
the target node answers 307 with the owner's URL. This client is the
other half of that contract — shared by the CLI (`--master` accepts a
comma-separated list) and the fleet bench's storm clients:

  * endpoints are tried in order starting from the last one that
    answered (sticky preference: a healthy replica keeps serving);
  * connection-level failures fail over to the next endpoint — but for
    NON-idempotent methods (POST/PUT/PATCH: mounts, removes, bulk
    batches carry no HTTP-level idempotency key) only failures that
    prove the request never reached a server (connection refused, DNS)
    fail over; an ambiguous failure (timeout, reset mid-exchange)
    surfaces instead — the first replica may have already mounted, and
    re-sending would double-allocate;
  * 307/302/301 redirects are followed up to `max_redirects`, re-sending
    the body (unlike urllib, which refuses redirected POSTs) — exactly
    what a redirected /removetpu or /batch/addtpu needs;
  * 503 (degraded worker / unowned shard) fails over to the next
    endpoint once before surfacing — another replica may own the shard
    by now.

stdlib-only, like the CLI it serves.
"""

from __future__ import annotations

import json as jsonlib
import socket
import urllib.error
import urllib.parse
import urllib.request

from gpumounter_tpu.utils.log import get_logger

logger = get_logger("rpc.http_failover")


class EndpointError(OSError):
    """Every endpoint failed at the transport level."""


#: methods safe to re-send to another replica after ANY transport
#: failure. Mutations are not in here: the HTTP API carries no
#: idempotency key, so an ambiguous failure must surface.
_IDEMPOTENT_METHODS = frozenset({"GET", "HEAD"})


def _never_reached_server(exc: Exception) -> bool:
    """True only for failures that prove the request was never sent:
    connection refused / no route / DNS. Timeouts and resets are
    ambiguous — the server may have processed the request."""
    reason = getattr(exc, "reason", exc)
    return isinstance(reason, (ConnectionRefusedError, socket.gaierror))


class MasterEndpoints:
    def __init__(self, masters: str | list[str], token: str | None = None,
                 timeout_s: float = 360.0, max_redirects: int = 4):
        if isinstance(masters, str):
            masters = masters.split(",")
        self.bases = [m.strip().rstrip("/") for m in masters if m.strip()]
        if not self.bases:
            raise ValueError("no master endpoints given")
        self.token = token
        self.timeout_s = timeout_s
        self.max_redirects = max_redirects
        self._preferred = 0

    # --- request plumbing ---

    def _headers(self, json_body, extra: dict | None) -> dict:
        headers = dict(extra or {})
        if json_body is not None:
            headers["Content-Type"] = "application/json"
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    @staticmethod
    def _encode(form, json_body) -> bytes | None:
        if json_body is not None:
            return jsonlib.dumps(json_body).encode()
        if form is not None:
            return urllib.parse.urlencode(form, doseq=True).encode()
        return None

    def _one(self, method: str, url: str, data: bytes | None,
             headers: dict) -> tuple[int, str, dict]:
        """One exchange; returns (status, body, response headers).
        HTTPError is an answer, not a failure — redirects and 4xx/5xx
        all carry meaning here. Transport errors propagate."""
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return resp.status, resp.read().decode(), dict(resp.headers)
        except urllib.error.HTTPError as exc:
            body = exc.read().decode()
            return exc.code, body, dict(exc.headers)

    def request(self, method: str, path: str, form: dict | None = None,
                json_body: dict | None = None,
                headers: dict | None = None) -> tuple[int, str]:
        """(status, body) from the first endpoint that answers, shard
        redirects followed. Raises EndpointError only when every
        endpoint fails at the transport level."""
        data = self._encode(form, json_body)
        send_headers = self._headers(json_body, headers)
        order = [(self._preferred + i) % len(self.bases)
                 for i in range(len(self.bases))]
        last_exc: Exception | None = None
        deferred_503: tuple[int, str] | None = None
        for idx in order:
            url = self.bases[idx] + path
            try:
                status, body = self._follow(method, url, data, send_headers)
            except EndpointError:
                raise  # redirect loop: a real answer, not unreachability
            except (urllib.error.URLError, OSError, TimeoutError) as exc:
                if method not in _IDEMPOTENT_METHODS \
                        and not _never_reached_server(exc):
                    # Ambiguous mutation outcome (timeout / mid-exchange
                    # reset): the replica may have executed it. Re-POSTing
                    # elsewhere could mount twice — surface instead.
                    raise EndpointError(
                        f"{method} {path} to {self.bases[idx]} failed "
                        f"ambiguously ({exc}); not retrying a mutation "
                        f"elsewhere — check `tpumounter audit` for "
                        f"whether it landed") from exc
                logger.warning("master %s unreachable (%s); failing over",
                               self.bases[idx], exc)
                last_exc = exc
                continue
            if status == 503 and deferred_503 is None \
                    and idx != order[-1]:
                # Unowned shard / degraded worker: one more replica may
                # route better. Remember the answer in case they all say
                # 503 — that IS the fleet's honest state then.
                deferred_503 = (status, body)
                continue
            self._preferred = idx
            return status, body
        if deferred_503 is not None:
            return deferred_503
        raise EndpointError(
            f"no master endpoint reachable (tried {self.bases}): "
            f"{last_exc}")

    def _follow(self, method: str, url: str, data: bytes | None,
                headers: dict) -> tuple[int, str]:
        """Follow shard redirects, re-sending method AND body (307
        semantics; urllib alone refuses redirected POSTs)."""
        for _ in range(self.max_redirects + 1):
            status, body, resp_headers = self._one(method, url, data,
                                                   headers)
            if status not in (301, 302, 307):
                return status, body
            location = next((v for k, v in resp_headers.items()
                             if k.lower() == "location"), None)
            if not location:
                return status, body
            url = urllib.parse.urljoin(url, location)
            logger.debug("following shard redirect to %s", url)
        raise EndpointError(
            f"redirect loop: more than {self.max_redirects} hops "
            f"(last: {url})")
