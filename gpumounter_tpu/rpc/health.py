"""Standard gRPC health service (grpc.health.v1.Health/Check).

The reference's worker has no health surface at all (SURVEY.md §5: "the
gRPC server has no health service"); kubelet/gRPC-aware probes expect this
exact protocol. Messages ride our wire codec — no grpcio-health-checking
dependency.
"""

from __future__ import annotations

from gpumounter_tpu.rpc.wire import Field, Message
from gpumounter_tpu.utils.lazy_grpc import grpc

SERVICE = "grpc.health.v1.Health"

SERVING = 1
NOT_SERVING = 2
SERVICE_UNKNOWN = 3


class HealthCheckRequest(Message):
    FIELDS = [Field(1, "service", "string")]


class HealthCheckResponse(Message):
    FIELDS = [Field(1, "status", "enum")]


def add_health_service(server: grpc.Server,
                       known_services: set[str] | None = None) -> None:
    known = known_services or set()

    def check(request: HealthCheckRequest, context) -> HealthCheckResponse:
        if request.service and known and request.service not in known:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"unknown service {request.service}")
        return HealthCheckResponse(status=SERVING)

    handler = grpc.method_handlers_generic_handler(
        SERVICE,
        {"Check": grpc.unary_unary_rpc_method_handler(
            check,
            request_deserializer=HealthCheckRequest.decode,
            response_serializer=lambda m: m.encode())})
    server.add_generic_rpc_handlers((handler,))


def check_health(address: str, service: str = "",
                 timeout_s: float = 5.0) -> int:
    """Client-side Check; returns the status enum value."""
    with grpc.insecure_channel(address) as channel:
        stub = channel.unary_unary(
            f"/{SERVICE}/Check",
            request_serializer=lambda m: m.encode(),
            response_deserializer=HealthCheckResponse.decode)
        resp = stub(HealthCheckRequest(service=service), timeout=timeout_s)
        return resp.status
