"""gRPC client for the worker's mount services.

Reference parity: the master dials workerIP:1200 insecure and calls
AddGPU/RemoveGPU (cmd/GPUMounter-master/main.go:82-96, 185-199). This client
speaks the TPU-native service names; `legacy=True` switches to the
reference's gpu_mount.* names for cross-testing.
"""

from __future__ import annotations

from gpumounter_tpu.rpc import api
from gpumounter_tpu.utils.lazy_grpc import grpc


_TOKEN_FROM_CONFIG = object()  # sentinel: resolve from global config


class WorkerClient:
    def __init__(self, address: str, timeout_s: float = 300.0,
                 legacy: bool = False, token=_TOKEN_FROM_CONFIG):
        """token: the worker's shared bearer secret (utils/auth.py).
        Default resolves TPUMOUNTER_AUTH_TOKEN[_FILE] from the global
        config; pass None to send no credentials (rejected by a worker
        in the default token mode)."""
        if token is _TOKEN_FROM_CONFIG:
            from gpumounter_tpu.config import get_config
            from gpumounter_tpu.utils.auth import resolve_token
            token = resolve_token(get_config())
        self._metadata = ((("authorization", f"Bearer {token}"),)
                          if token else None)
        self.address = address
        self.timeout_s = timeout_s
        self._channel = grpc.insecure_channel(address)
        add_service = api.ADD_SERVICE_LEGACY if legacy else api.ADD_SERVICE_TPU
        rem_service = (api.REMOVE_SERVICE_LEGACY if legacy
                       else api.REMOVE_SERVICE_TPU)
        add_method = api.ADD_METHOD if legacy else api.ADD_METHOD_TPU
        rem_method = api.REMOVE_METHOD if legacy else api.REMOVE_METHOD_TPU
        self._add = self._channel.unary_unary(
            f"/{add_service}/{add_method}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.AddTPUResponse.decode)
        self._remove = self._channel.unary_unary(
            f"/{rem_service}/{rem_method}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.RemoveTPUResponse.decode)
        # Probe/quiesce have no legacy analog; a reference worker answers
        # UNIMPLEMENTED, which callers treat as "health unknown".
        self._probe = self._channel.unary_unary(
            f"/{api.PROBE_SERVICE_TPU}/{api.PROBE_METHOD_TPU}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.ProbeTPUResponse.decode)
        self._quiesce = self._channel.unary_unary(
            f"/{api.QUIESCE_SERVICE_TPU}/{api.QUIESCE_METHOD_TPU}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.QuiesceStatusResponse.decode)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def add_tpu(self, pod_name: str, namespace: str, tpu_num: int,
                is_entire_mount: bool = False) -> api.AddTPUResult:
        result, _ = self.add_tpu_detailed(pod_name, namespace, tpu_num,
                                          is_entire_mount)
        return result

    def add_tpu_detailed(self, pod_name: str, namespace: str, tpu_num: int,
                         is_entire_mount: bool = False,
                         prefer_ici: bool = False,
                         ) -> tuple[api.AddTPUResult, list[str]]:
        """(result, mounted device uuids) — uuids empty unless Success."""
        resp = self._add(api.AddTPURequest(
            pod_name=pod_name, namespace=namespace, tpu_num=tpu_num,
            is_entire_mount=is_entire_mount, prefer_ici=prefer_ici),
            timeout=self.timeout_s,
            metadata=self._metadata)
        return api.AddTPUResult(resp.add_tpu_result), list(resp.uuids)

    def quiesce_status(self, pod_name: str, namespace: str,
                       ) -> tuple["api.QuiesceStatusResult",
                                  "api.QuiesceStatusResponse"]:
        """(result, raw response) — the migration orchestrator's read-back
        of the tenant's ack annotation + live chip holder count."""
        resp = self._quiesce(api.QuiesceStatusRequest(
            pod_name=pod_name, namespace=namespace), timeout=self.timeout_s,
            metadata=self._metadata)
        return api.QuiesceStatusResult(resp.quiesce_status_result), resp

    def probe_tpu(self, pod_name: str, namespace: str,
                  ) -> tuple[api.ProbeTPUResult, list[api.ChipHealth]]:
        """(result, per-chip health for every chip the pod holds)."""
        resp = self._probe(api.ProbeTPURequest(
            pod_name=pod_name, namespace=namespace), timeout=self.timeout_s,
            metadata=self._metadata)
        return api.ProbeTPUResult(resp.probe_tpu_result), list(resp.chips)

    def remove_tpu(self, pod_name: str, namespace: str, uuids: list[str],
                   force: bool = False,
                   remove_all: bool = False) -> api.RemoveTPUResult:
        resp = self._remove(api.RemoveTPURequest(
            pod_name=pod_name, namespace=namespace, uuids=list(uuids),
            force=force, remove_all=remove_all), timeout=self.timeout_s,
            metadata=self._metadata)
        return api.RemoveTPUResult(resp.remove_tpu_result)
