"""gRPC client for the worker's mount services.

Reference parity: the master dials workerIP:1200 insecure and calls
AddGPU/RemoveGPU (cmd/GPUMounter-master/main.go:82-96, 185-199). This client
speaks the TPU-native service names; `legacy=True` switches to the
reference's gpu_mount.* names for cross-testing.

Resilience (rpc/resilience.py): every call gets a per-method deadline
from config (overridable per call via `timeout_s=`), a bounded
capped-exponential retry on retriable transport codes, and — when the
caller wires one in — a per-worker circuit breaker that fails fast while
the worker is degraded. AddTPU/RemoveTPU carry idempotency keys so a
retried mutation is answered from the worker's completion record instead
of mounting twice. Transport failures surface as typed errors
(DeadlineExceededError, WorkerUnavailableError, BreakerOpenError).

Failpoint sites (gpumounter_tpu/faults):
  rpc.client.call       delay / drop (unavailable) / error every outbound
                        attempt (ctx: method, address)
  rpc.client.deadline   return(seconds) overrides the resolved deadline
"""

from __future__ import annotations

import secrets
import threading
import time

from gpumounter_tpu.faults import failpoints
from gpumounter_tpu.obs import trace
from gpumounter_tpu.rpc import api
from gpumounter_tpu.rpc.resilience import (
    RPC_RETRIES,
    BreakerOpenError,
    CircuitBreaker,
    DeadlineExceededError,
    FencedError,
    RetryPolicy,
    WorkerUnavailableError,
)
from gpumounter_tpu.utils.lazy_grpc import grpc
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("rpc.client")

CHANNEL_POOL_HITS = REGISTRY.counter(
    "tpumounter_channel_pool_hits_total",
    "Worker RPCs served over an already-established pooled channel")
CHANNEL_POOL_MISSES = REGISTRY.counter(
    "tpumounter_channel_pool_misses_total",
    "Pool lookups that had to dial a fresh channel")
CHANNEL_POOL_EVICTIONS = REGISTRY.counter(
    "tpumounter_channel_pool_evictions_total",
    "Pooled channels closed, by reason (idle / invalidated / pruned / "
    "shutdown)")
CHANNEL_POOL_SIZE = REGISTRY.gauge(
    "tpumounter_channel_pool_size",
    "Live channels currently held by the pool")


class ChannelPool:
    """Per-address cached gRPC channels with keepalive + idle eviction.

    The reference master dials a brand-new TCP connection for every RPC
    (cmd/GPUMounter-master/main.go:82,185), paying connect + HTTP/2
    handshake on the mount critical path each time; round 3 of this
    build inherited that via `_client_factory` constructing a fresh
    `WorkerClient` (and channel) per request. The pool makes the dial a
    once-per-worker cost: every `WorkerClient` built with `channel_pool=`
    borrows the shared channel and its `close()` only drops the
    reference — the pool owns channel lifetime.

    Invalidation keeps cached channels honest:
      * `invalidate(address)` — wired to the circuit breaker's open
        transition (a worker that just ate `failure_threshold` transport
        errors gets a fresh dial when it comes back) and to registry
        address changes (a replaced worker pod's old IP must not serve
        one more RPC);
      * `retain(active)` — registry churn sweep, same lifecycle as
        CircuitBreaker.prune;
      * idle eviction after `channel_idle_evict_s` on the lookup path.

    Accounting (`stats()`) is exact — dialed == closed + live always —
    so the chaos harness can assert no channel leaks (invariant 7).
    """

    def __init__(self, cfg=None):
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        self.idle_evict_s = cfg.channel_idle_evict_s
        self.keepalive_time_s = cfg.channel_keepalive_time_s
        self._lock = threading.Lock()
        #: address -> [channel, last_used_monotonic, borrowers]
        self._channels: dict[str, list] = {}
        self._dialed = 0
        self._closed = 0
        self._shutdown = False

    # --- the borrow path ---

    def channel(self, address: str):
        now = time.monotonic()
        to_close = []
        try:
            with self._lock:
                if self._shutdown:
                    raise RuntimeError("channel pool is shut down")
                to_close = self._sweep_locked(now)
                entry = self._channels.get(address)
                if entry is not None:
                    entry[1] = now
                    entry[2] += 1
                    CHANNEL_POOL_HITS.inc()
                    return entry[0]
                ch = grpc.insecure_channel(address, options=(
                    ("grpc.keepalive_time_ms",
                     int(self.keepalive_time_s * 1000)),
                    ("grpc.keepalive_timeout_ms", 5000),
                    ("grpc.keepalive_permit_without_calls", 1),
                ))
                self._channels[address] = [ch, now, 1]
                self._dialed += 1
                CHANNEL_POOL_MISSES.inc()
                CHANNEL_POOL_SIZE.set(float(len(self._channels)))
                return ch
        finally:
            self._close_channels(to_close, "idle")

    def release(self, address: str) -> None:
        """A borrower (WorkerClient.close) is done with the channel: it
        stays pooled, but the idle clock restarts now and the in-use
        guard drops. No-op if the entry was invalidated meanwhile."""
        with self._lock:
            entry = self._channels.get(address)
            if entry is not None:
                entry[1] = time.monotonic()
                entry[2] = max(0, entry[2] - 1)

    def _sweep_locked(self, now: float) -> list:
        """Caller holds the lock; returns channels to close outside it.
        In-use entries (live borrowers) are never idle-evicted — a slow
        RPC on worker A must not have its transport closed because a
        lookup for worker B happened to sweep."""
        if self.idle_evict_s <= 0:
            return []
        stale = [addr for addr, (_, used, refs) in self._channels.items()
                 if refs <= 0 and now - used > self.idle_evict_s]
        out = [self._channels.pop(addr)[0] for addr in stale]
        if out:
            self._closed += len(out)
            CHANNEL_POOL_SIZE.set(float(len(self._channels)))
        return out

    def _close_channels(self, channels: list, reason: str) -> None:
        """Close channels already removed (and counted) under the lock."""
        for ch in channels:
            try:
                ch.close()
            except Exception as exc:  # noqa: BLE001 — grpc teardown
                logger.warning("pooled channel close failed: %s", exc)
            CHANNEL_POOL_EVICTIONS.inc(reason=reason)

    # --- invalidation ---

    def invalidate(self, address: str, reason: str = "invalidated") -> None:
        """Drop an address even if borrowed: the callers (breaker-open,
        address change) know the transport is dead/wrong — an in-flight
        RPC on it is failing anyway."""
        with self._lock:
            entry = self._channels.pop(address, None)
            if entry is not None:
                self._closed += 1
            CHANNEL_POOL_SIZE.set(float(len(self._channels)))
        if entry is not None:
            logger.info("channel to %s invalidated (%s)", address, reason)
            self._close_channels([entry[0]], reason)

    def retain(self, active_addresses) -> None:
        """Close every pooled channel whose address is not in the active
        set (registry churn: replaced/deleted workers)."""
        active = set(active_addresses)
        with self._lock:
            stale = [a for a in self._channels if a not in active]
            out = [self._channels.pop(a)[0] for a in stale]
            self._closed += len(out)
            CHANNEL_POOL_SIZE.set(float(len(self._channels)))
        self._close_channels(out, "pruned")

    def close_all(self) -> None:
        with self._lock:
            out = [entry[0] for entry in self._channels.values()]
            self._closed += len(out)
            self._channels.clear()
            self._shutdown = True
            CHANNEL_POOL_SIZE.set(0.0)
        self._close_channels(out, "shutdown")

    # --- accounting (chaos invariant 7) ---

    def live_count(self) -> int:
        with self._lock:
            return len(self._channels)

    def stats(self) -> dict:
        with self._lock:
            return {"live": len(self._channels), "dialed": self._dialed,
                    "closed": self._closed}

def _grpc_details(exc: Exception) -> str:
    details = getattr(exc, "details", None)
    if callable(details):
        try:
            return str(details() or "")
        except Exception:  # noqa: BLE001 — non-grpc .details() callables
            return ""
    return ""


_TOKEN_FROM_CONFIG = object()  # sentinel: resolve from global config

#: gRPC codes worth another bounded attempt. Safe for mutations because
#: AddTPU/RemoveTPU are idempotent under their key; Probe/Quiesce are
#: read-only.
_RETRIABLE_CODE_NAMES = frozenset({"UNAVAILABLE", "DEADLINE_EXCEEDED"})

#: methods whose retry safety depends on the worker honoring the
#: idempotency key — a legacy (reference) worker skips that field, so
#: retrying them against one could mount twice.
_MUTATION_METHODS = frozenset({"AddTPU", "RemoveTPU"})


class WorkerClient:
    def __init__(self, address: str, timeout_s: float | None = None,
                 legacy: bool = False, token=_TOKEN_FROM_CONFIG,
                 cfg=None, retry: RetryPolicy | None = None,
                 breaker: CircuitBreaker | None = None,
                 breaker_key: str | None = None,
                 channel_pool: ChannelPool | None = None):
        """token: the worker's shared bearer secret (utils/auth.py).
        Default resolves TPUMOUNTER_AUTH_TOKEN[_FILE] from the global
        config; pass None to send no credentials (rejected by a worker
        in the default token mode).

        timeout_s: uniform deadline override for every method; None (the
        default) uses the per-method deadlines from config
        (rpc_{add,remove,probe,quiesce}_timeout_s).

        breaker/breaker_key: a shared CircuitBreaker (usually the
        WorkerRegistry's) and the key to report under; omitted = no
        breaker participation (standalone/CLI use).

        channel_pool: a shared ChannelPool — the client borrows the
        pooled per-address channel (reused across requests, keepalive
        on) and its close() only drops the reference; omitted = the
        client dials and owns a private channel (old behavior)."""
        if cfg is None:
            from gpumounter_tpu.config import get_config
            cfg = get_config()
        if token is _TOKEN_FROM_CONFIG:
            from gpumounter_tpu.utils.auth import resolve_token
            token = resolve_token(cfg)
        self._metadata = ((("authorization", f"Bearer {token}"),)
                          if token else None)
        self.address = address
        self.timeout_s = timeout_s
        self.timeouts = {
            "AddTPU": cfg.rpc_add_timeout_s,
            "RemoveTPU": cfg.rpc_remove_timeout_s,
            "ProbeTPU": cfg.rpc_probe_timeout_s,
            "QuiesceStatus": cfg.rpc_quiesce_timeout_s,
            "CollectTelemetry": cfg.rpc_telemetry_timeout_s,
        }
        self.retry = retry or RetryPolicy(
            max_attempts=cfg.rpc_max_attempts,
            base_s=cfg.rpc_retry_base_s, cap_s=cfg.rpc_retry_cap_s)
        self.breaker = breaker
        self.breaker_key = breaker_key or address
        self._legacy = legacy
        self._pool = channel_pool
        if channel_pool is not None:
            self._channel = channel_pool.channel(address)
            self._owns_channel = False
        else:
            self._channel = grpc.insecure_channel(address)
            self._owns_channel = True
        add_service = api.ADD_SERVICE_LEGACY if legacy else api.ADD_SERVICE_TPU
        rem_service = (api.REMOVE_SERVICE_LEGACY if legacy
                       else api.REMOVE_SERVICE_TPU)
        add_method = api.ADD_METHOD if legacy else api.ADD_METHOD_TPU
        rem_method = api.REMOVE_METHOD if legacy else api.REMOVE_METHOD_TPU
        self._add = self._channel.unary_unary(
            f"/{add_service}/{add_method}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.AddTPUResponse.decode)
        self._remove = self._channel.unary_unary(
            f"/{rem_service}/{rem_method}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.RemoveTPUResponse.decode)
        # Probe/quiesce have no legacy analog; a reference worker answers
        # UNIMPLEMENTED, which callers treat as "health unknown".
        self._probe = self._channel.unary_unary(
            f"/{api.PROBE_SERVICE_TPU}/{api.PROBE_METHOD_TPU}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.ProbeTPUResponse.decode)
        self._quiesce = self._channel.unary_unary(
            f"/{api.QUIESCE_SERVICE_TPU}/{api.QUIESCE_METHOD_TPU}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.QuiesceStatusResponse.decode)
        # Telemetry has no legacy analog either; a reference worker
        # answers UNIMPLEMENTED and the fleet collector falls back to
        # scraping the worker's HTTP /metrics (obs/fleet.py).
        self._telemetry = self._channel.unary_unary(
            f"/{api.TELEMETRY_SERVICE_TPU}/{api.TELEMETRY_METHOD_TPU}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=api.CollectTelemetryResponse.decode)

    def close(self) -> None:
        channel, self._channel = self._channel, None
        if channel is None:  # idempotent: with-block + explicit close
            return
        if self._owns_channel:
            channel.close()
        elif self._pool is not None:
            # Pooled channels stay open — the pool owns their lifetime;
            # release drops the in-use guard and restarts the idle clock.
            self._pool.release(self.address)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --- the resilient call core ---

    @staticmethod
    def _code_name(exc: Exception) -> str:
        if isinstance(exc, failpoints.InjectedUnavailable):
            return "UNAVAILABLE"
        code = getattr(exc, "code", None)
        if callable(code):
            try:
                return getattr(code(), "name", "") or "UNKNOWN"
            except Exception:  # noqa: BLE001 — non-grpc .code() callables
                return "UNKNOWN"
        return ""

    def _call(self, method: str, stub, request, timeout_s: float | None):
        if self._channel is None:
            raise RuntimeError(f"WorkerClient for {self.address} is closed")
        with trace.span(f"rpc.{method}", address=self.address):
            # Stamp the span we just opened onto the wire: the worker's
            # server-side span parents to THIS rpc span, not the caller's.
            request.trace_context = trace.wire_context()
            return self._call_attempts(method, stub, request, timeout_s)

    def _call_attempts(self, method: str, stub, request,
                       timeout_s: float | None):
        deadline = (timeout_s if timeout_s is not None
                    else self.timeout_s if self.timeout_s is not None
                    else self.timeouts[method])
        deadline = float(failpoints.value("rpc.client.deadline", deadline,
                                          method=method))
        last_exc: Exception | None = None
        for attempt in range(1, self.retry.max_attempts + 1):
            if self.breaker is not None:
                retry_after = self.breaker.allow(self.breaker_key)
                if retry_after is not None:
                    raise BreakerOpenError(
                        f"worker {self.address} degraded (circuit open); "
                        f"retry in {retry_after:.1f}s", retry_after,
                        self.address, method) from last_exc
            try:
                failpoints.fire("rpc.client.call", method=method,
                                address=self.address)
                response = stub(request, timeout=deadline,
                                metadata=self._metadata)
            except Exception as exc:  # noqa: BLE001 — gRPC boundary
                code = self._code_name(exc)
                transport = code in _RETRIABLE_CODE_NAMES
                # A legacy peer ignores the idempotency key, so a retried
                # mutation could land twice there — never retry those.
                retriable = transport and not (
                    self._legacy and method in _MUTATION_METHODS)
                if self.breaker is not None:
                    # Only transport-level failures degrade the worker: an
                    # application error (FAILED_PRECONDITION, INTERNAL...)
                    # proves it is alive and answering.
                    if transport:
                        self.breaker.record_failure(self.breaker_key)
                    else:
                        self.breaker.record_success(self.breaker_key)
                if not retriable or attempt >= self.retry.max_attempts:
                    raise self._typed(exc, code, method) from exc
                last_exc = exc
                delay = self.retry.delay_for(attempt)
                RPC_RETRIES.inc(method=method)
                logger.warning(
                    "%s to %s failed (%s, attempt %d/%d); retrying in "
                    "%.2fs", method, self.address, code or exc, attempt,
                    self.retry.max_attempts, delay)
                time.sleep(delay)
            else:
                if self.breaker is not None:
                    self.breaker.record_success(self.breaker_key)
                return response
        raise AssertionError("unreachable")  # loop always returns/raises

    def _typed(self, exc: Exception, code: str, method: str) -> Exception:
        if code == "DEADLINE_EXCEEDED":
            return DeadlineExceededError(
                f"{method} to {self.address} exceeded its deadline",
                self.address, method)
        if code == "UNAVAILABLE":
            return WorkerUnavailableError(
                f"{method} to {self.address}: worker unavailable ({exc})",
                self.address, method)
        if code == "FAILED_PRECONDITION":
            # Epoch fencing rejections travel as FAILED_PRECONDITION with
            # a "FENCED:" detail prefix (worker/server.py). Typed so
            # callers (and never the retry loop — application errors are
            # not retriable here) can distinguish "my shard view is
            # stale" from a policy rejection like CanMount.
            detail = _grpc_details(exc)
            if detail.startswith("FENCED"):
                return FencedError(
                    f"{method} to {self.address}: {detail}",
                    self.address, method)
        return exc  # non-transport errors keep their original type

    # --- methods ---

    def add_tpu(self, pod_name: str, namespace: str, tpu_num: int,
                is_entire_mount: bool = False,
                timeout_s: float | None = None,
                epoch: int = 0) -> api.AddTPUResult:
        result, _ = self.add_tpu_detailed(pod_name, namespace, tpu_num,
                                          is_entire_mount,
                                          timeout_s=timeout_s,
                                          epoch=epoch)
        return result

    def add_tpu_detailed(self, pod_name: str, namespace: str, tpu_num: int,
                         is_entire_mount: bool = False,
                         prefer_ici: bool = False,
                         timeout_s: float | None = None,
                         idempotency_key: str | None = None,
                         epoch: int = 0,
                         share_weight: int = 0,
                         share_rate_budget: int = 0,
                         ) -> tuple[api.AddTPUResult, list[str]]:
        """(result, mounted device uuids) — uuids empty unless Success.

        One idempotency key covers the whole bounded-retry loop: a retry
        whose first attempt actually landed on the worker gets the
        recorded response back instead of a second mount.

        epoch: the caller's fencing epoch for the target node (0 =
        unfenced). A stale epoch raises FencedError — never retried.

        share_weight/share_rate_budget: fractional (vchip) grant policy;
        share_weight > 0 makes every mounted chip a policy-carrying
        fractional grant (rate budget 0 = unmetered)."""
        request = api.AddTPURequest(
            pod_name=pod_name, namespace=namespace, tpu_num=tpu_num,
            is_entire_mount=is_entire_mount, prefer_ici=prefer_ici,
            idempotency_key=idempotency_key or f"add-{secrets.token_hex(8)}",
            epoch=int(epoch), share_weight=int(share_weight),
            share_rate_budget=int(share_rate_budget))
        resp = self._call("AddTPU", self._add, request, timeout_s)
        return api.AddTPUResult(resp.add_tpu_result), list(resp.uuids)

    def quiesce_status(self, pod_name: str, namespace: str,
                       timeout_s: float | None = None,
                       ) -> tuple["api.QuiesceStatusResult",
                                  "api.QuiesceStatusResponse"]:
        """(result, raw response) — the migration orchestrator's read-back
        of the tenant's ack annotation + live chip holder count."""
        resp = self._call("QuiesceStatus", self._quiesce,
                          api.QuiesceStatusRequest(
                              pod_name=pod_name, namespace=namespace),
                          timeout_s)
        return api.QuiesceStatusResult(resp.quiesce_status_result), resp

    def collect_telemetry(self, timeout_s: float | None = None,
                          quarantined: bool = False,
                          ) -> "api.CollectTelemetryResponse":
        """One worker's telemetry snapshot (raw response; the JSON in
        .telemetry parses via obs.fleet.parse_telemetry). Read-only —
        safe to retry like Probe/Quiesce. `quarantined` piggybacks the
        master's health verdict for this node (the worker drains its
        warm pool while flagged; see health/plane.py)."""
        return self._call("CollectTelemetry", self._telemetry,
                          api.CollectTelemetryRequest(
                              quarantined=bool(quarantined)), timeout_s)

    def probe_tpu(self, pod_name: str, namespace: str,
                  timeout_s: float | None = None,
                  ) -> tuple[api.ProbeTPUResult, list[api.ChipHealth]]:
        """(result, per-chip health for every chip the pod holds)."""
        resp = self._call("ProbeTPU", self._probe,
                          api.ProbeTPURequest(
                              pod_name=pod_name, namespace=namespace),
                          timeout_s)
        return api.ProbeTPUResult(resp.probe_tpu_result), list(resp.chips)

    def remove_tpu(self, pod_name: str, namespace: str, uuids: list[str],
                   force: bool = False,
                   remove_all: bool = False,
                   timeout_s: float | None = None,
                   idempotency_key: str | None = None,
                   epoch: int = 0) -> api.RemoveTPUResult:
        request = api.RemoveTPURequest(
            pod_name=pod_name, namespace=namespace, uuids=list(uuids),
            force=force, remove_all=remove_all,
            idempotency_key=idempotency_key or f"rm-{secrets.token_hex(8)}",
            epoch=int(epoch))
        resp = self._call("RemoveTPU", self._remove, request, timeout_s)
        return api.RemoveTPUResult(resp.remove_tpu_result)
