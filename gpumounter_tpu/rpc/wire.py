"""Hand-rolled protobuf (proto3) wire-format codec.

Replaces the reference's generated code (pkg/api/gpu-mount/api.pb.go, 481
lines of protoc output) and its protoc/runtime version coupling with a small
declarative codec: a message is a dataclass-like class with a FIELDS spec;
encode/decode speak the real protobuf wire format, so the same codec talks to
the kubelet's pod-resources gRPC server (a real protobuf peer) and carries our
own master<->worker RPC contract.

Wire format essentials (proto3):
  tag = (field_number << 3) | wire_type
  wire_type 0 = varint (int32/int64/uint32/uint64/bool/enum; zigzag for sint*)
  wire_type 1 = 64-bit  (fixed64/double)
  wire_type 2 = length-delimited (string/bytes/embedded message/packed repeated)
  wire_type 5 = 32-bit  (fixed32/float)
Unknown fields are skipped on decode (forward compatibility).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any

VARINT, I64, LEN, I32 = 0, 1, 2, 5

_SCALAR_KINDS = frozenset({
    "int32", "int64", "uint32", "uint64", "bool", "enum",
    "string", "bytes", "double", "float", "fixed64", "fixed32",
})


def encode_varint(value: int) -> bytes:
    if value < 0:
        # proto3 negative int32/int64/enum are encoded as 10-byte two's
        # complement varints (64-bit sign extension).
        value += 1 << 64
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _to_signed64(value: int) -> int:
    return value - (1 << 64) if value >= 1 << 63 else value


def _to_signed32(value: int) -> int:
    value &= 0xFFFFFFFFFFFFFFFF
    value = _to_signed64(value)
    # int32 fields arriving as 64-bit varints: truncate like protobuf does.
    value &= 0xFFFFFFFF
    return value - (1 << 32) if value >= 1 << 31 else value


@dataclass(frozen=True)
class Field:
    number: int
    name: str
    kind: str               # one of _SCALAR_KINDS or "message"
    repeated: bool = False
    message: type | None = None  # for kind == "message"

    def __post_init__(self):
        if self.kind == "message":
            if self.message is None:
                raise ValueError(f"field {self.name}: message kind needs a class")
        elif self.kind not in _SCALAR_KINDS:
            raise ValueError(f"field {self.name}: unknown kind {self.kind}")


def _default_for(field: Field) -> Any:
    if field.repeated:
        return []
    if field.kind == "message":
        return None
    if field.kind in ("string",):
        return ""
    if field.kind == "bytes":
        return b""
    if field.kind == "bool":
        return False
    if field.kind in ("double", "float"):
        return 0.0
    return 0


class Message:
    """Base class: subclasses define FIELDS: list[Field]."""

    FIELDS: list[Field] = []
    __field_by_num: dict[int, Field]

    def __init__(self, **kwargs: Any):
        spec = {f.name: f for f in self.FIELDS}
        for f in self.FIELDS:
            setattr(self, f.name, _default_for(f))
        for k, v in kwargs.items():
            if k not in spec:
                raise TypeError(f"{type(self).__name__} has no field {k!r}")
            setattr(self, k, v)

    # ---- encoding ----

    def encode(self) -> bytes:
        out = bytearray()
        for f in self.FIELDS:
            value = getattr(self, f.name)
            if f.repeated:
                for item in value:
                    _encode_single(out, f, item)
            else:
                if _is_default(f, value):
                    continue  # proto3: defaults are omitted
                _encode_single(out, f, value)
        return bytes(out)

    # ---- decoding ----

    @classmethod
    def decode(cls, data: bytes):
        msg = cls()
        by_num = {f.number: f for f in cls.FIELDS}
        pos = 0
        while pos < len(data):
            tag, pos = decode_varint(data, pos)
            num, wt = tag >> 3, tag & 7
            f = by_num.get(num)
            if f is None:
                pos = _skip(data, pos, wt)
                continue
            pos = _decode_into(msg, f, data, pos, wt)
        return msg

    # ---- ergonomics ----

    def __repr__(self) -> str:
        parts = []
        for f in self.FIELDS:
            v = getattr(self, f.name)
            if f.repeated and not v:
                continue
            if not f.repeated and _is_default(f, v):
                continue
            parts.append(f"{f.name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"

    def __eq__(self, other: object) -> bool:
        if type(other) is not type(self):
            return NotImplemented
        return all(getattr(self, f.name) == getattr(other, f.name) for f in self.FIELDS)

    __hash__ = None  # mutable message: explicitly unhashable


def _is_default(f: Field, value: Any) -> bool:
    if f.kind == "message":
        return value is None
    return value == _default_for(f)


def _encode_single(out: bytearray, f: Field, value: Any) -> None:
    kind = f.kind
    if kind in ("int32", "int64", "uint32", "uint64", "bool", "enum"):
        out += encode_varint((f.number << 3) | VARINT)
        out += encode_varint(int(value))
    elif kind == "string":
        payload = value.encode("utf-8")
        out += encode_varint((f.number << 3) | LEN)
        out += encode_varint(len(payload))
        out += payload
    elif kind == "bytes":
        out += encode_varint((f.number << 3) | LEN)
        out += encode_varint(len(value))
        out += bytes(value)
    elif kind == "message":
        payload = value.encode()
        out += encode_varint((f.number << 3) | LEN)
        out += encode_varint(len(payload))
        out += payload
    elif kind == "double":
        out += encode_varint((f.number << 3) | I64)
        out += struct.pack("<d", value)
    elif kind == "fixed64":
        out += encode_varint((f.number << 3) | I64)
        out += struct.pack("<Q", value)
    elif kind == "float":
        out += encode_varint((f.number << 3) | I32)
        out += struct.pack("<f", value)
    elif kind == "fixed32":
        out += encode_varint((f.number << 3) | I32)
        out += struct.pack("<I", value)
    else:  # pragma: no cover - guarded by Field.__post_init__
        raise AssertionError(kind)


def _decode_scalar(f: Field, data: bytes, pos: int, wt: int) -> tuple[Any, int]:
    kind = f.kind
    if wt == VARINT:
        raw, pos = decode_varint(data, pos)
        if kind == "bool":
            return bool(raw), pos
        if kind in ("int32", "enum"):
            return _to_signed32(raw), pos
        if kind == "int64":
            return _to_signed64(raw), pos
        return raw, pos  # uint32/uint64
    if wt == LEN:
        size, pos = decode_varint(data, pos)
        payload = data[pos:pos + size]
        if len(payload) != size:
            raise ValueError("truncated length-delimited field")
        pos += size
        if kind == "string":
            return payload.decode("utf-8"), pos
        if kind == "bytes":
            return payload, pos
        raise ValueError(f"unexpected LEN payload for {f.name}")
    if wt == I64:
        payload = data[pos:pos + 8]
        if len(payload) != 8:
            raise ValueError("truncated 64-bit field")
        pos += 8
        if kind == "double":
            return struct.unpack("<d", payload)[0], pos
        return struct.unpack("<Q", payload)[0], pos
    if wt == I32:
        payload = data[pos:pos + 4]
        if len(payload) != 4:
            raise ValueError("truncated 32-bit field")
        pos += 4
        if kind == "float":
            return struct.unpack("<f", payload)[0], pos
        return struct.unpack("<I", payload)[0], pos
    raise ValueError(f"unsupported wire type {wt}")


def _decode_into(msg: Message, f: Field, data: bytes, pos: int, wt: int) -> int:
    if f.kind == "message":
        if wt != LEN:
            raise ValueError(f"message field {f.name} with wire type {wt}")
        size, pos = decode_varint(data, pos)
        payload = data[pos:pos + size]
        if len(payload) != size:
            raise ValueError("truncated embedded message")
        pos += size
        value = f.message.decode(payload)
        if f.repeated:
            getattr(msg, f.name).append(value)
        else:
            setattr(msg, f.name, value)
        return pos

    # packed repeated scalars (proto3 default for numeric repeated fields)
    if f.repeated and wt == LEN and f.kind not in ("string", "bytes"):
        size, pos = decode_varint(data, pos)
        end = pos + size
        if end > len(data):
            raise ValueError("truncated packed field")
        elem_wt = (I64 if f.kind in ("double", "fixed64")
                   else I32 if f.kind in ("float", "fixed32") else VARINT)
        items = getattr(msg, f.name)
        while pos < end:
            value, pos = _decode_scalar(f, data, pos, elem_wt)
            items.append(value)
        return pos

    value, pos = _decode_scalar(f, data, pos, wt)
    if f.repeated:
        getattr(msg, f.name).append(value)
    else:
        setattr(msg, f.name, value)
    return pos


def _skip(data: bytes, pos: int, wt: int) -> int:
    if wt == VARINT:
        _, pos = decode_varint(data, pos)
        return pos
    if wt == I64:
        pos += 8
    elif wt == LEN:
        size, pos = decode_varint(data, pos)
        pos += size
    elif wt == I32:
        pos += 4
    else:
        raise ValueError(f"cannot skip wire type {wt}")
    if pos > len(data):
        raise ValueError("truncated field while skipping")
    return pos


