"""Master <-> worker RPC contract.

Wire-compatible with the reference contract (pkg/api/gpu-mount/api.proto):
same field numbers, same result enums (including the reference's quirk that
RemoveGPUResult has no value 3 and GPUNotFound = 4, api.proto:25-41), so a
client written against the reference's proto can talk to our worker. Services
are registered under both the TPU-native names (tpu_mount.AddTPUService /
RemoveTPUService) and the reference names (gpu_mount.AddGPUService /
RemoveGPUService) for drop-in compatibility.
"""

from __future__ import annotations

import enum

from gpumounter_tpu.rpc.wire import Field, Message


class AddTPUResult(enum.IntEnum):
    # Reference: AddGPUResponse.AddGPUResult (api.proto:12-17)
    Success = 0
    InsufficientTPU = 1
    PodNotFound = 2


class RemoveTPUResult(enum.IntEnum):
    # Reference: RemoveGPUResponse.RemoveGPUResult (api.proto:32-39).
    # Value 3 intentionally absent; TPUNotFound = 4 matches GPUNotFound = 4.
    Success = 0
    TPUBusy = 1
    PodNotFound = 2
    TPUNotFound = 4


class AddTPURequest(Message):
    # Reference: AddGPURequest (api.proto:4-9). Field 5 is our extension:
    # ask the allocator to prefer an ICI-contiguous chip block
    # (allocator/placement.py — allocate-and-trim). Field 6 makes retries
    # safe: the worker remembers recently-completed keys and answers a
    # retried mount from that record instead of mounting again (the
    # client's bounded retry + the chaos harness depend on it).
    # Field 7 carries the caller's trace context (obs/trace.py,
    # "<trace_id>-<span_id>") so worker-side spans join the trace minted
    # at the master HTTP edge; the worker tolerates absent/malformed
    # values (legacy or buggy peers) by starting a fresh trace.
    # Field 8 is the fencing epoch (recovery plane): masters stamp the
    # node's monotonic epoch (bumped on shard takeover) on every
    # mutating RPC; the worker persists the highest seen and rejects
    # older non-zero epochs FENCED — closing the split-brain window
    # where a partitioned old shard owner mutates a node the new owner
    # already manages. 0 (the proto3 default, i.e. legacy/unsharded
    # masters) never fences.
    # Fields 9-10 carry the fractional (vchip) share policy: a
    # share_weight > 0 turns the grant into a policy-carrying fractional
    # grant — every chip this request mounts gets a policy-map entry
    # (QoS weight + token rate budget, cgroup/ebpf.py) instead of a
    # whole-chip static rule, recorded in the worker ledger's share
    # records for crash replay. share_weight == 0 (the proto3 default,
    # i.e. every legacy caller) keeps exact whole-chip semantics.
    # share_rate_budget == 0 means unmetered.
    # Wire-compatible: legacy peers skip the unknown fields and see
    # reference semantics.
    FIELDS = [
        Field(1, "pod_name", "string"),
        Field(2, "namespace", "string"),
        Field(3, "tpu_num", "int32"),
        Field(4, "is_entire_mount", "bool"),
        Field(5, "prefer_ici", "bool"),
        Field(6, "idempotency_key", "string"),
        Field(7, "trace_context", "string"),
        Field(8, "epoch", "int64"),
        Field(9, "share_weight", "int32"),
        Field(10, "share_rate_budget", "int32"),
    ]


class AddTPUResponse(Message):
    # Reference: AddGPUResponse (api.proto:11-19). Field 2 is our
    # extension: the device ids actually mounted, so callers (the slice
    # coordinator's rollback in particular) can undo exactly this
    # operation. Wire-compatible — proto3 decoders skip unknown fields,
    # so clients built against the reference proto still work.
    FIELDS = [
        Field(1, "add_tpu_result", "enum"),
        Field(2, "uuids", "string", repeated=True),
    ]


class RemoveTPURequest(Message):
    # Reference: RemoveGPURequest (api.proto:25-30); uuids -> device ids.
    # Field 5 is our extension: remove every slave-held chip regardless of
    # mount type (the slice coordinator's remove path). Field 6 mirrors
    # AddTPURequest: a retried remove whose first attempt landed answers
    # Success from the worker's idempotency record. Field 7 mirrors
    # AddTPURequest's trace context; field 8 its fencing epoch.
    # Wire-compatible — legacy peers skip the unknown fields and see
    # reference semantics.
    FIELDS = [
        Field(1, "pod_name", "string"),
        Field(2, "namespace", "string"),
        Field(3, "uuids", "string", repeated=True),
        Field(4, "force", "bool"),
        Field(5, "remove_all", "bool"),
        Field(6, "idempotency_key", "string"),
        Field(7, "trace_context", "string"),
        Field(8, "epoch", "int64"),
    ]


class RemoveTPUResponse(Message):
    # Reference: RemoveGPUResponse (api.proto:32-41)
    FIELDS = [
        Field(1, "remove_tpu_result", "enum"),
    ]


# --- chip health probing (no reference analog) ---
#
# The elastic reconciler's eyes on each node: which chips does this pod
# actually hold, and are they alive? "Alive" = the host device node still
# stats as the same char device AND the injected node is still present in
# the target's /dev; holder_count carries the /proc fd-scan result so
# callers can distinguish a dead-but-held chip (JAX process wedged on it)
# from an idle one.


class ProbeTPUResult(enum.IntEnum):
    Success = 0
    PodNotFound = 1


class ProbeTPURequest(Message):
    FIELDS = [
        Field(1, "pod_name", "string"),
        Field(2, "namespace", "string"),
        Field(3, "trace_context", "string"),
    ]


class ChipHealth(Message):
    FIELDS = [
        Field(1, "uuid", "string"),
        Field(2, "healthy", "bool"),
        Field(3, "reason", "string"),
        Field(4, "holder_count", "int32"),
    ]


class ProbeTPUResponse(Message):
    FIELDS = [
        Field(1, "probe_tpu_result", "enum"),
        Field(2, "chips", "message", repeated=True, message=ChipHealth),
    ]


# --- migration quiesce read-back (no reference analog) ---
#
# The migration orchestrator signals the tenant through the
# tpumounter.io/migration-phase annotation (jaxside.watch_migration) and
# needs eyes on the other side: did the tenant ack the phase (it packs
# state and stamps tpumounter.io/migration-ack), and do any processes
# still hold the chips? The worker is the natural reader — it already
# resolves the pod's container and runs the /proc holder scan.


class QuiesceStatusResult(enum.IntEnum):
    Success = 0
    PodNotFound = 1


class QuiesceStatusRequest(Message):
    FIELDS = [
        Field(1, "pod_name", "string"),
        Field(2, "namespace", "string"),
        Field(3, "trace_context", "string"),
    ]


class QuiesceStatusResponse(Message):
    FIELDS = [
        Field(1, "quiesce_status_result", "enum"),
        Field(2, "acked_id", "string"),      # migration id the tenant acked
        Field(3, "acked_phase", "string"),   # "quiesced" / "resumed" / ""
        Field(4, "holder_count", "int32"),   # PIDs holding any chip
        Field(5, "chip_count", "int32"),     # chips the pod currently holds
    ]


# --- fleet telemetry collection (no reference analog) ---
#
# The master's fleet collector (obs/fleet.py) periodically pulls every
# worker's local telemetry — mount-latency histogram, warm-pool and
# mount counters, per-tenant device-access counts, program-swap count —
# over the pooled channels it already holds. The payload travels as one
# JSON document in a string field (schema obs.fleet.TELEMETRY_SCHEMA):
# the rollup shape evolves faster than the wire should, and proto3
# string fields keep legacy decoders skipping it cleanly. A legacy
# (reference) worker has no TelemetryService at all and answers
# UNIMPLEMENTED — the collector then degrades to scraping the worker's
# HTTP /metrics exposition. Absent or malformed payloads parse to None
# (obs.fleet.parse_telemetry) and trigger the same scrape fallback,
# never an error.


class CollectTelemetryResult(enum.IntEnum):
    Success = 0


class CollectTelemetryRequest(Message):
    FIELDS = [
        Field(1, "trace_context", "string"),
        # Health-plane verdict for the dialed node, carried on the
        # collector's pull so the worker needs no extra RPC to learn
        # it: while true the worker drains its warm holder pods and
        # pauses refill (a quarantined node must not bank standby
        # capacity nobody may adopt). Absent/false (older masters)
        # means not quarantined — fail open.
        Field(2, "quarantined", "bool"),
    ]


class CollectTelemetryResponse(Message):
    FIELDS = [
        Field(1, "collect_telemetry_result", "enum"),
        Field(2, "node_name", "string"),   # informational; the collector
                                           # keys by the node it dialed
        Field(3, "telemetry", "string"),   # JSON telemetry snapshot
    ]


# gRPC method descriptors: (service_full_name, method, request_cls, response_cls)
ADD_SERVICE_TPU = "tpu_mount.AddTPUService"
REMOVE_SERVICE_TPU = "tpu_mount.RemoveTPUService"
PROBE_SERVICE_TPU = "tpu_mount.ProbeTPUService"  # our extension; no legacy name
QUIESCE_SERVICE_TPU = "tpu_mount.QuiesceStatusService"  # ditto
TELEMETRY_SERVICE_TPU = "tpu_mount.TelemetryService"    # ditto
# Reference service names (api.proto:21-23, 43-45) for drop-in clients.
ADD_SERVICE_LEGACY = "gpu_mount.AddGPUService"
REMOVE_SERVICE_LEGACY = "gpu_mount.RemoveGPUService"

ADD_METHOD = "AddGPU"          # reference method name (api.proto:22)
REMOVE_METHOD = "RemoveGPU"    # reference method name (api.proto:44)
ADD_METHOD_TPU = "AddTPU"
REMOVE_METHOD_TPU = "RemoveTPU"
PROBE_METHOD_TPU = "ProbeTPU"
QUIESCE_METHOD_TPU = "QuiesceStatus"
TELEMETRY_METHOD_TPU = "CollectTelemetry"
