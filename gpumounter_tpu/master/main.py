"""Master daemon entrypoint.

Reference parity: cmd/GPUMounter-master/main.go:230-246 — init logger,
route table, serve on :8080.
"""

from __future__ import annotations

from gpumounter_tpu.config import get_config
from gpumounter_tpu.utils.log import get_logger, init_logger

logger = get_logger("master.main")


def main() -> None:
    cfg = get_config()
    init_logger(cfg.log_dir, "tpumounter-master.log")
    from gpumounter_tpu.obs import assembly, audit, flight, trace
    trace.configure(cfg)
    audit.configure(cfg)
    flight.configure(cfg)
    assembly.configure(cfg)
    from gpumounter_tpu.k8s import default_client
    from gpumounter_tpu.master.app import MasterApp, build_http_server

    kube = default_client()
    app = MasterApp(kube, cfg=cfg)
    httpd = build_http_server(app)
    # Sharded masters (TPUMOUNTER_SHARD_COUNT > 1): start the lease
    # acquire/renew loop. A takeover — initial claims and adopting a
    # crashed peer's shards alike — re-drives that shard's interrupted
    # migrations from the journals; intents follow at the next elastic
    # resync tick (the reconciler's not-owned gate flips).
    if cfg.shard_count > 1:
        def _on_takeover(shards: set) -> None:
            adopted_now = app.migrations.resume_interrupted()
            if adopted_now:
                logger.warning(
                    "shard takeover %s: re-driving %d interrupted "
                    "migration(s): %s", sorted(shards), len(adopted_now),
                    ", ".join(adopted_now))

        app.shards.on_takeover = _on_takeover
        app.shards.start()
        logger.info("shard manager on: %d shards, replica %s, lease "
                    "%.0fs", app.shards.shard_count,
                    app.shards.replica_id, app.shards.duration_s)
    # The elastic loop re-reads intents from pod annotations on start, so
    # declared desires survive master restarts with no extra store.
    app.elastic.start()
    # Recovery controller: watch worker liveness + node readiness and
    # evacuate confirmed-dead nodes (release bookings, re-drive intents
    # and migration journals). Detection state is in-memory — a fresh
    # replica re-confirms within one grace window.
    if cfg.recovery_enabled:
        app.recovery.start()
        logger.info("recovery controller on (interval %.0fs, confirm "
                    "%d failures + %.0fs grace)", cfg.recovery_interval_s,
                    cfg.recovery_confirm_failures, cfg.recovery_grace_s)
    # ICI defragmenter background loop (opt-in via TPUMOUNTER_DEFRAG):
    # every DEFRAG_INTERVAL_S plan against a fresh capacity snapshot and
    # execute when the plan has moves. Plans are in-memory (re-computed
    # cheaply after a restart); the per-move migration journals are what
    # crash-recover, through resume_interrupted below like any other
    # migration.
    if cfg.defrag_enabled:
        app.defrag.start()
        logger.info("defragmenter on (interval %.0fs, target block %d, "
                    "max %d moves/plan)", cfg.defrag_interval_s,
                    cfg.defrag_target_block, cfg.defrag_max_moves)
    # Autoscale decision loop (opt-in via TPUMOUNTER_AUTOSCALE): every
    # AUTOSCALE_INTERVAL_S fit the per-tenant throughput curves from
    # the fleet rollup and turn queue/utilization trends into elastic
    # intent updates. All state is in-memory (the model re-learns from
    # live telemetry within a few scrapes) — a restart just means a few
    # quiet passes before the controller trusts its fits again.
    if cfg.autoscale_enabled:
        app.autoscale.start()
        logger.info("autoscaler on (interval %.0fs, cooldown %.0fs, "
                    "max step %d)", cfg.autoscale_interval_s,
                    cfg.autoscale_cooldown_s, cfg.autoscale_max_step)
    # Canary prober: active gray-failure probes (synthetic mount ->
    # verify -> unmount) against suspect/quarantined nodes. The passive
    # scorer rides the fleet collect pass and needs no thread of its
    # own; quarantine state was already reloaded from the store seam in
    # MasterApp.__init__, so a takeover keeps the set.
    if cfg.health_enabled and cfg.health_canary_interval_s > 0:
        app.canary.start()
        logger.info("health plane on (canary every %.0fs, quarantine "
                    "budget %.0f%%)", cfg.health_canary_interval_s,
                    cfg.health_quarantine_budget * 100)
    # Fleet telemetry poll loop: federate every worker's telemetry each
    # FLEET_SCRAPE_INTERVAL_S and evaluate the SLO burn rates (breaches
    # emit k8s Events + audit records). Restart-safe: workers report
    # absolute counters and the rollup is node-keyed, so a restarted
    # collector never double-counts.
    app.fleet.start()
    # Migrations journal to pod annotations the same way: a master that
    # died mid-migration re-adopts and re-drives it from the recorded
    # phase instead of leaving a tenant half-drained.
    adopted = app.migrations.resume_interrupted()
    if adopted:
        logger.warning("re-driving %d interrupted migration(s): %s",
                       len(adopted), ", ".join(adopted))
    logger.info("tpumounter master serving on :%d (elastic reconciler on, "
                "resync %.0fs)", cfg.master_port,
                cfg.elastic_resync_interval_s)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if cfg.autoscale_enabled:
            app.autoscale.stop()
        if cfg.defrag_enabled:
            app.defrag.stop()
        app.canary.stop()
        app.recovery.stop()
        app.fleet.stop()
        app.elastic.stop()
        if cfg.shard_count > 1:
            # Graceful handoff: release held leases so peers take the
            # shards immediately instead of waiting out the TTL.
            app.shards.stop(release=True)
        httpd.shutdown()


if __name__ == "__main__":
    main()
