"""Master HTTP gateway.

Reference parity — cmd/GPUMounter-master/main.go:
  * Routes (main.go:232-234):
      GET  /addgpu/namespace/:ns/pod/:pod/gpu/:n/isEntireMount/:bool
      POST /removegpu/namespace/:ns/pod/:pod/force/:bool   (form: uuids)
      GET  /
    plus TPU-native aliases /addtpu/.../tpu/:n/... and /removetpu/...
  * Target pod lookup to find its node (main.go:52-66).
  * Worker discovery by listing labeled pods (findAllWorker, main.go:248-268)
    — but cached with a TTL here instead of one LIST per request
    (SURVEY.md §3 hot-loop fix).
  * gRPC to worker `podIP:1200` (main.go:82,185) via rpc.client.WorkerClient.
  * Result→HTTP mapping kept exactly: Add Success→200 body "Add ... Success",
    Insufficient→500, PodNotFound→400 (main.go:103-116); Remove
    PodNotFound/Busy/NotFound→400, Success→200 (main.go:206-224).

Additions over the reference (SURVEY.md §5 gaps): /healthz, /metrics,
/devices inventory endpoint, structured 404s.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from gpumounter_tpu.config import get_config
from gpumounter_tpu.k8s.client import KubeClient, NotFoundError
from gpumounter_tpu.k8s.types import Pod
from gpumounter_tpu.obs import trace
from gpumounter_tpu.obs.audit import AUDIT, audited
from gpumounter_tpu.rpc import api
from gpumounter_tpu.rpc.client import WorkerClient
from gpumounter_tpu.utils.locks import OrderedLock
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("master")

#: stamped on replica-to-replica proxied requests (bulk sub-batches):
#: a forwarded request is answered locally — non-owned targets get a
#: per-target error instead of another hop, so ownership flaps can
#: never turn into a proxy loop.
FORWARDED_HEADER = "x-tpumounter-forwarded"


class WorkerRegistry:
    """node name → worker pod IP, kept current by a background watch.

    Reference re-LISTs the worker pods on every request (main.go:68,171);
    round 1 of this build TTL-cached but still LISTed on expiry, on every
    miss, and on every /workers hit (VERDICT r1 weak #3). Informer shape
    now: one LIST primes the cache, then a watch stream applies
    ADDED/MODIFIED/DELETED deltas in place. Reads are pure cache hits; a
    miss triggers at most one rate-limited re-LIST to cover a lagging
    watch meeting a brand-new worker.
    """

    #: floor between on-miss re-LISTs (ADVICE r1: back-to-back LIST storm)
    MISS_RELIST_INTERVAL_S = 1.0

    def __init__(self, kube: KubeClient, cfg=None, store=None):
        self.kube = kube
        self.cfg = cfg or get_config()
        # Worker discovery goes through the MasterStore seam: the
        # registry is pure derived state any replica rebuilds from the
        # cluster (store/base.py — the stateless-master contract).
        if store is None:
            from gpumounter_tpu.store import KubeMasterStore
            store = KubeMasterStore(kube, self.cfg)
        self.store = store
        # Per-worker circuit breaker, keyed by worker address: shared by
        # every WorkerClient the master builds, so consecutive transport
        # failures anywhere in the control plane degrade the entry (the
        # HTTP routes answer 503 + Retry-After, the reconciler backs off)
        # until a half-open probe succeeds (rpc/resilience.py).
        from gpumounter_tpu.rpc.resilience import CircuitBreaker
        self.breaker = CircuitBreaker(
            failure_threshold=self.cfg.breaker_failure_threshold,
            reset_s=self.cfg.breaker_reset_s)
        # Shared per-address channel pool (rpc/client.py): every
        # WorkerClient the master builds borrows its worker's cached
        # channel instead of dialing fresh TCP per request. Kept honest
        # by the same lifecycle that prunes the breaker, plus the
        # breaker's open transition (a degraded worker's channel is
        # dropped so recovery starts from a fresh dial).
        from gpumounter_tpu.rpc.client import ChannelPool
        self.channel_pool = ChannelPool(cfg=self.cfg)
        self.breaker.on_open = (
            lambda key: self.channel_pool.invalidate(key, "breaker-open"))
        # node name → (worker pod IP, worker pod name). The pod name makes
        # DELETED eviction exact even when the terminal event no longer
        # carries a podIP (names are unique per namespace at any instant).
        self._cache: dict[str, tuple[str, str]] = {}
        self._lock = OrderedLock("registry.cache")
        # serializes miss-path LISTs; always taken BEFORE registry.cache
        self._refresh_mu = OrderedLock("registry.refresh")
        self._primed = threading.Event()
        self._last_list = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Watch events that land while a LIST is in flight (a _miss_refresh
        # racing the watch thread) are journaled and replayed on top of the
        # LIST result before the swap, so a delta observed between the LIST
        # response and the cache swap is never lost (it used to be silently
        # dropped until the next watch re-open, ~60 s).
        self._journal: list[tuple[str, Pod]] | None = None

    # --- lifecycle ---

    def _ensure_started(self) -> None:
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._watch_loop, name="worker-registry-watch",
                    daemon=True)
                self._thread.start()
        # First caller blocks until the watch thread's priming LIST lands
        # (bounded: a broken API server must not hang requests forever).
        self._primed.wait(10.0)

    def stop(self) -> None:
        self._stop.set()
        self.channel_pool.close_all()

    # --- cache maintenance ---

    @staticmethod
    def _apply_to(cache: dict[str, tuple[str, str]], etype: str,
                  pod: Pod) -> None:
        if not pod.node_name:
            return
        entry = cache.get(pod.node_name)
        if etype == "DELETED":
            # Evict only if the entry still belongs to THIS pod (by
            # name — terminal events may carry no podIP): during a
            # rolling update the replacement's ADDED can land before
            # the old pod's DELETED, and popping unconditionally
            # would evict the live replacement.
            if entry is not None and entry[1] == pod.name:
                cache.pop(pod.node_name, None)
            return
        if pod.pod_ip:
            cache[pod.node_name] = (pod.pod_ip, pod.name)

    def _apply(self, etype: str, pod: Pod) -> None:
        with self._lock:
            old = self._cache.get(pod.node_name) if pod.node_name else None
            self._apply_to(self._cache, etype, pod)
            new = self._cache.get(pod.node_name) if pod.node_name else None
            if self._journal is not None:  # a LIST is in flight: journal too
                self._journal.append((etype, pod))
        if old is not None and (new is None or new[0] != old[0]):
            # The node's worker address changed or vanished: its cached
            # channel must not serve one more RPC to the old IP.
            self.channel_pool.invalidate(
                f"{old[0]}:{self.cfg.worker_port}", "address-change")
        if etype == "DELETED":
            self._prune_breaker()

    def _refresh(self) -> None:
        with self._refresh_mu:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        """LIST + journal-merged swap. Caller holds _refresh_mu (only one
        LIST may be in flight — a second would stomp the journal)."""
        with self._lock:
            self._journal = []
        try:
            pods = self.store.list_worker_pods()
            cache: dict[str, tuple[str, str]] = {}
            for pod_json in pods:
                p = Pod(pod_json)
                if p.node_name and p.pod_ip:
                    cache[p.node_name] = (p.pod_ip, p.name)
            with self._lock:
                # Watch deltas that raced the LIST win over its snapshot.
                for etype, pod in self._journal:
                    self._apply_to(cache, etype, pod)
                self._cache = cache
                self._last_list = time.monotonic()
        finally:
            with self._lock:
                self._journal = None
        self._prune_breaker()
        self._primed.set()

    #: watch-reconnect backoff. A stream that lived for less than
    #: MIN_HEALTHY_WATCH_S did no useful work — the classic case is the
    #: fake/apiserver ending it immediately (backlog overrun -> the
    #: 410-Gone-like end, or a flapping LB), and the old loop would
    #: re-LIST + re-open in a zero-sleep spin exactly when the API was
    #: most overloaded. Consecutive short-lived streams now back off
    #: exponentially with full jitter (so N replicas' registries don't
    #: reconnect in lockstep); one healthy stream resets the clock.
    MIN_HEALTHY_WATCH_S = 5.0
    WATCH_BACKOFF_BASE_S = 0.5
    WATCH_BACKOFF_CAP_S = 15.0

    def _watch_backoff(self, failures: int) -> float:
        import random
        cap = min(self.WATCH_BACKOFF_CAP_S,
                  self.WATCH_BACKOFF_BASE_S * 2 ** max(0, failures - 1))
        return random.uniform(cap / 2, cap)

    def _watch_loop(self) -> None:
        short_streams = 0
        while not self._stop.is_set():
            opened = time.monotonic()
            try:
                # (Re)prime, then stream deltas. Re-LIST on every watch
                # re-open keeps the cache honest across missed windows.
                self._refresh()
                watch = self.store.watch_worker_pods(timeout_s=60.0)
                for etype, pod_json in watch:
                    if self._stop.is_set():
                        return
                    self._apply(etype, Pod(pod_json))
            except Exception as exc:  # noqa: BLE001 — keep the informer up
                # A stream that lived past the healthy threshold before
                # erroring did useful work: reset the escalation (count
                # this failure as the first), else hours-apart transport
                # errors would ratchet the backoff to its cap forever.
                if time.monotonic() - opened >= self.MIN_HEALTHY_WATCH_S:
                    short_streams = 1
                else:
                    short_streams += 1
                delay = self._watch_backoff(short_streams)
                logger.warning("worker watch failed (%s); retrying in "
                               "%.1fs", exc, delay)
                self._stop.wait(delay)
                continue
            if time.monotonic() - opened >= self.MIN_HEALTHY_WATCH_S:
                short_streams = 0
                continue
            # The stream ended almost immediately without an error (the
            # fake's trimmed-backlog end / a real 410 Gone): this is the
            # tight-loop shape — back off with jitter before the
            # re-LIST + re-open.
            short_streams += 1
            delay = self._watch_backoff(short_streams)
            logger.info("worker watch stream ended after %.2fs "
                        "(%d short stream(s)); re-opening in %.1fs",
                        time.monotonic() - opened, short_streams, delay)
            self._stop.wait(delay)

    def _prune_breaker(self) -> None:
        """Evicted workers take their breaker state (and any standing
        degraded gauge) and their pooled channel with them — a replaced
        worker at a new IP must not leave a permanently-open series or
        a cached connection for the dead address."""
        with self._lock:
            active = {f"{ip}:{self.cfg.worker_port}"
                      for ip, _ in self._cache.values()}
        self.breaker.prune(active)
        self.channel_pool.retain(active)

    # --- reads (cache-only; one rate-limited LIST on miss) ---

    def registry_snapshot(self) -> dict[str, str]:
        self._ensure_started()
        with self._lock:
            return {node: ip for node, (ip, _) in self._cache.items()}

    def _miss_refresh(self) -> None:
        """One rate-limited LIST for a cache miss: concurrent misses
        serialize here and re-check the stamp, so N simultaneous requests
        for an unknown node cost one LIST, not N."""
        with self._refresh_mu:
            with self._lock:
                if time.monotonic() - self._last_list \
                        <= self.MISS_RELIST_INTERVAL_S:
                    return
            self._refresh_locked()

    def worker_address(self, node_name: str) -> str | None:
        self._ensure_started()
        with self._lock:
            entry = self._cache.get(node_name)
        if entry is None:
            self._miss_refresh()  # brand-new worker the watch hasn't seen
            with self._lock:
                entry = self._cache.get(node_name)
        if entry is None:
            return None
        return f"{entry[0]}:{self.cfg.worker_port}"


class _HttpError(Exception):
    def __init__(self, status: int, message: str,
                 headers: dict[str, str] | None = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers or {}


_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(
        r"^/add(?:gpu|tpu)/namespace/(?P<ns>[^/]+)/pod/(?P<pod>[^/]+)"
        r"/(?:gpu|tpu)/(?P<num>[^/]+)/isEntireMount/(?P<entire>[^/]+)$"),
     "add"),
    ("POST", re.compile(
        r"^/remove(?:gpu|tpu)/namespace/(?P<ns>[^/]+)/pod/(?P<pod>[^/]+)"
        r"/force/(?P<force>[^/]+)$"),
     "remove"),
    # Bulk mount: one request -> many pod/chip mounts, grouped by owning
    # shard (proxied to peers) and node (one pooled channel per node).
    ("POST", re.compile(r"^/batch/addtpu$"), "batch_add"),
    # Shard table: which replica owns which shard (master/shard.py).
    ("GET", re.compile(r"^/shards$"), "shards"),
    ("GET", re.compile(r"^/$"), "index"),
    ("GET", re.compile(r"^/healthz$"), "healthz"),
    # API-outage degraded mode (k8s/health.py + store/cache.py): the
    # ApiHealth verdict, the store cache's staleness stamps, and the
    # write-behind queue's books — the RUNBOOK's "Surviving an
    # API-server outage" pane.
    ("GET", re.compile(r"^/apihealth$"), "apihealth"),
    ("GET", re.compile(r"^/metrics$"), "metrics"),
    ("GET", re.compile(r"^/workers$"), "workers"),
    ("POST", re.compile(r"^/addslice$"), "addslice"),
    ("POST", re.compile(r"^/removeslice$"), "removeslice"),
    # Elastic intents: declarative chip counts the reconciler converges
    # toward (gpumounter_tpu/elastic/). CRUD over pod annotations.
    ("GET", re.compile(r"^/intents$"), "intents_list"),
    ("GET", re.compile(
        r"^/intents/(?P<ns>[^/]+)/(?P<pod>[^/]+)$"), "intent_get"),
    ("PUT", re.compile(
        r"^/intents/(?P<ns>[^/]+)/(?P<pod>[^/]+)$"), "intent_put"),
    ("DELETE", re.compile(
        r"^/intents/(?P<ns>[^/]+)/(?P<pod>[^/]+)$"), "intent_delete"),
    # Live migration: move a tenant's whole chip set between pods
    # without a restart (gpumounter_tpu/migrate/).
    ("POST", re.compile(r"^/migrate$"), "migrate_start"),
    ("GET", re.compile(r"^/migrations$"), "migrations_list"),
    ("GET", re.compile(r"^/migrations/(?P<mid>[^/]+)$"), "migration_get"),
    ("POST", re.compile(
        r"^/migrations/(?P<mid>[^/]+)/abort$"), "migration_abort"),
    # Observability reads (gpumounter_tpu/obs). The audit/timeline
    # patterns capture their own query strings because the dispatcher
    # matches the raw request path (no other route accepts queries).
    ("GET", re.compile(r"^/audit(?:\?(?P<query>.*))?$"), "audit"),
    ("GET", re.compile(r"^/trace/(?P<tid>[^/?]+)$"), "trace"),
    # Incident flight recorder (gpumounter_tpu/obs/flight.py): the
    # merged chronological timeline — root/error spans, audit records,
    # k8s Events, ApiHealth transitions, recovery markers.
    ("GET", re.compile(r"^/timeline(?:\?(?P<query>.*))?$"), "timeline"),
    # Fleet telemetry plane (gpumounter_tpu/obs/fleet.py + slo.py): one
    # pane over every node's mount latency / warm-pool / device-access
    # telemetry, and the SLO burn-rate evaluation over it.
    ("GET", re.compile(r"^/fleet$"), "fleet"),
    ("GET", re.compile(r"^/slo$"), "slo"),
    # Capacity & fragmentation plane (gpumounter_tpu/obs/capacity.py):
    # per-host chip inventory rolled into fragmentation indices, the
    # per-size allocation-feasibility table and the headroom forecast.
    # Captures its own query string (?accel_type=) like /audit.
    ("GET", re.compile(r"^/capacity(?:\?(?P<query>.*))?$"), "capacity"),
    # Tenant-perceived disruption ledger (jaxside telemetry SDK ->
    # worker tenant store -> fleet merge): per-tenant step rates and
    # disruption windows, each joined to its control-plane trace.
    ("GET", re.compile(r"^/tenants$"), "tenants"),
    # Node-failure recovery plane (gpumounter_tpu/recovery/): per-node
    # liveness verdicts + the evacuation history, and a manual
    # evacuation trigger for operators who confirmed a death themselves.
    ("GET", re.compile(r"^/recovery$"), "recovery"),
    ("POST", re.compile(
        r"^/recovery/evacuate/(?P<node>[^/]+)$"), "recovery_evacuate"),
    # Gray-failure health plane (gpumounter_tpu/health/): per-node
    # quarantine state machine over the fleet telemetry + canary
    # probes. One read pane + the manual quarantine/release verb
    # (body {"action": "quarantine"|"release"}).
    ("GET", re.compile(r"^/health/nodes$"), "health_nodes"),
    ("POST", re.compile(
        r"^/health/quarantine/(?P<node>[^/]+)$"), "health_quarantine"),
    # ICI defragmenter (gpumounter_tpu/defrag/): the plane that acts on
    # /capacity's `admissible-after-defrag` verdicts — plans a
    # minimal-cost live-migration sequence and drives it with the
    # checkpoint-assisted drain. One read pane + three operator verbs.
    ("GET", re.compile(r"^/defrag$"), "defrag"),
    ("POST", re.compile(r"^/defrag/plan$"), "defrag_plan"),
    ("POST", re.compile(r"^/defrag/run$"), "defrag_run"),
    ("POST", re.compile(r"^/defrag/pause$"), "defrag_pause"),
    # Fractional chip shares (gpumounter_tpu/vchip/): the share books
    # (who holds what fraction of which chip at what QoS weight) and
    # the co-location admission controller that fills them.
    ("GET", re.compile(r"^/shares$"), "shares"),
    ("POST", re.compile(r"^/shares$"), "shares_admit"),
    ("DELETE", re.compile(
        r"^/shares/(?P<ns>[^/]+)/(?P<pod>[^/]+)$"), "shares_release"),
    # Closed-loop autoscaler (gpumounter_tpu/autoscale/): the decision
    # pane (model fits, gate verdicts, recent decisions) + the audited
    # operator pause/resume verbs.
    ("GET", re.compile(r"^/autoscale$"), "autoscale"),
    ("POST", re.compile(r"^/autoscale/pause$"), "autoscale_pause"),
    ("POST", re.compile(r"^/autoscale/resume$"), "autoscale_resume"),
    ("POST", re.compile(r"^/autoscale/evaluate$"), "autoscale_evaluate"),
]


def _parse_bool(raw: str, param: str) -> bool:
    low = raw.lower()
    if low in ("true", "1", "t"):
        return True
    if low in ("false", "0", "f"):
        return False
    raise _HttpError(400, f"Invalid param {param}: {raw} "
                          "(should be true or false)")


class MasterApp:
    """Transport-independent request handling; served by build_http_server."""

    #: routes that stay open without a bearer token: read-only liveness
    #: surfaces (k8s probes cannot attach credentials). Everything else
    #: — mount/unmount, slice ops, the worker-topology listing —
    #: requires auth.
    UNAUTHENTICATED_ROUTES = frozenset({"index", "healthz"})

    #: read-only observability routes: a distinct read scope
    #: (TPUMOUNTER_AUTH_READ_TOKEN[_FILE]) instead of piggybacking on
    #: the mutate token. With a read token configured they accept it
    #: (the mutate token always implies read); without one, /metrics
    #: stays open (probe/scrape back-compat) while /audit, /trace,
    #: /fleet and /slo — which reveal pod/tenant names and chip
    #: movements — require the mutate token.
    READ_ROUTES = frozenset({"metrics", "audit", "trace", "fleet", "slo",
                             "shards", "recovery", "tenants",
                             "apihealth", "timeline", "capacity",
                             "defrag", "shares", "health_nodes",
                             "autoscale"})

    #: mutating routes whose edge outcome lands in the audit trail
    #: (worker-side records carry the chip-level detail for the same
    #: trace id).
    AUDITED_ROUTES = frozenset({
        "add", "remove", "batch_add", "addslice", "removeslice",
        "intent_put", "intent_delete", "migrate_start",
        "migration_abort", "recovery_evacuate", "health_quarantine",
        "defrag_plan", "defrag_run", "defrag_pause", "shares_admit",
        "shares_release", "autoscale_pause", "autoscale_resume",
        "autoscale_evaluate"})

    def __init__(self, kube: KubeClient, cfg=None,
                 worker_client_factory=None,
                 registry: WorkerRegistry | None = None,
                 store=None, shards=None):
        from gpumounter_tpu.utils.auth import (
            required_token,
            resolve_read_token,
        )
        self.cfg = cfg or get_config()
        # Fail-closed at construction (daemon startup): the reference
        # serves its HTTP API open to any in-cluster peer even though
        # removegpu force=true kills tenant PIDs; here serving without a
        # secret requires the explicit TPUMOUNTER_AUTH=insecure opt-in.
        self._token = required_token(self.cfg, "master HTTP gateway")
        self._read_token = resolve_read_token(self.cfg)
        # API-outage degraded mode (k8s/health.py): every API call this
        # replica makes feeds one per-endpoint ApiHealth state machine
        # (healthy/degraded/down with hysteresis), surfaced on /healthz
        # + /apihealth and consulted by every subsystem before it acts
        # destructively on API-derived state.
        from gpumounter_tpu.k8s.health import api_health, wrap_health
        self.apihealth = api_health(cfg=self.cfg)
        self.kube = wrap_health(kube, self.apihealth)
        kube = self.kube
        # All durable master state flows through one MasterStore
        # (store/base.py): registry, intents, and journals are derived
        # views any replica — this one restarted, or a peer taking over
        # a shard — rebuilds identically from the cluster. The default
        # store wears the degraded-mode wrapper (store/cache.py): reads
        # fall back to a bounded-staleness cache during an outage, and
        # annotation writes defer into the durable write-behind queue,
        # replayed exactly-once on reconnect.
        if store is None:
            from gpumounter_tpu.store import (
                CachedMasterStore,
                KubeMasterStore,
                WatchMasterStore,
            )
            # TPUMOUNTER_WATCH_STORE=1 swaps the list-backed inner
            # store for the watch/informer-backed one (store/watch.py)
            # — O(result) index reads instead of O(fleet) API lists.
            # The outage cache layers ABOVE either one unchanged.
            if self.cfg.store_watch_enabled:
                inner = WatchMasterStore(kube, self.cfg)
            else:
                inner = KubeMasterStore(kube, self.cfg)
            store = CachedMasterStore(inner, cfg=self.cfg,
                                      apihealth=self.apihealth)
        self.store = store
        # Shard ownership (master/shard.py): inactive by default (one
        # master owns everything, zero overhead); master/main.py starts
        # the lease loop when TPUMOUNTER_SHARD_COUNT > 1. Requests for
        # nodes another replica owns 307-redirect (single-target) or
        # proxy (bulk) to the owner's advertised URL.
        if shards is None:
            from gpumounter_tpu.master.shard import ShardManager
            shards = ShardManager(kube, cfg=self.cfg)
        self.shards = shards
        # Admission control: bound the client requests one replica
        # processes concurrently (0 = unbounded, the legacy shape).
        # Replica-to-replica forwarded work runs under its own separate
        # bound — never the client gate — so two replicas proxying to
        # each other cannot deadlock on their own admission slots. Its
        # size is the legitimate maximum: every OTHER replica's entire
        # admitted load could forward here at once (depth x (N-1)), so
        # the gate only trips on runaway peers, never on traffic the
        # entry gates already admitted — a smaller gate would throttle
        # proxied sub-batches below the fleet's own admission capacity
        # and invert the scale-out.
        depth = int(self.cfg.master_http_concurrency)
        self._client_gate = (threading.BoundedSemaphore(depth)
                             if depth > 0 else None)
        forward_depth = depth * max(1, int(self.cfg.shard_count) - 1)
        self._forward_gate = (threading.BoundedSemaphore(forward_depth)
                              if depth > 0 else None)
        self.registry = registry or WorkerRegistry(kube, self.cfg,
                                                   store=self.store)
        # The default worker client forwards the same per-deploy secret
        # the worker's gRPC interceptor checks, reports transport
        # outcomes to the registry's shared per-worker circuit breaker,
        # and borrows the registry's pooled channel (no fresh TCP dial
        # per request — SURVEY §3 control-plane hot path).
        self._client_factory = worker_client_factory or (
            lambda addr: WorkerClient(
                addr, token=self._token, cfg=self.cfg,
                breaker=self.registry.breaker, breaker_key=addr,
                channel_pool=self.registry.channel_pool))
        # Elastic intent controller: constructed here so the routes and
        # the loop share one store/queue; the loop thread only runs after
        # an explicit elastic.start() (master/main.py — tests drive
        # reconcile_once directly or start it themselves).
        from gpumounter_tpu.elastic import ElasticReconciler
        from gpumounter_tpu.elastic.intents import IntentStore
        self.elastic = ElasticReconciler(
            kube, self.registry, self._client_factory, cfg=self.cfg,
            store=IntentStore(kube, self.cfg, backend=self.store),
            shards=self.shards, apihealth=self.apihealth)
        # Live-migration orchestrator: shares the registry and worker
        # client factory; interrupted migrations are re-adopted by an
        # explicit migrations.resume_interrupted() (master/main.py).
        from gpumounter_tpu.migrate import MigrationCoordinator
        self.migrations = MigrationCoordinator(
            kube, self.registry, self._client_factory, cfg=self.cfg,
            store=self.store, shards=self.shards,
            apihealth=self.apihealth)
        # Fleet telemetry plane: the collector federates every worker's
        # telemetry over the same pooled channels and feeds the SLO
        # burn-rate engine; breaches land as k8s Events + audit records.
        # The background poll loop only runs after an explicit
        # fleet.start() (master/main.py) — the /fleet and /slo routes
        # collect on demand when the rollup is stale, so tests and the
        # CLI work without it.
        from gpumounter_tpu.obs.fleet import FleetCollector
        from gpumounter_tpu.obs.slo import SloEngine
        self.slo = SloEngine(cfg=self.cfg, kube=kube)
        self.fleet = FleetCollector(self.registry, self._client_factory,
                                    cfg=self.cfg, slo=self.slo,
                                    shards=self.shards)
        # Capacity & fragmentation plane (obs/capacity.py): observes
        # every fleet collection pass (fragmentation gauges + the
        # slice-feasibility SLO counters) and serves /capacity from the
        # same node entries. Registered process-globally so the elastic
        # reconciler's capacity-limited branch can stamp rejection
        # verdicts without holding a reference.
        from gpumounter_tpu.obs import capacity as capacity_obs
        self.capacity = capacity_obs.CapacityPlane(
            self.fleet, cfg=self.cfg, elastic=self.elastic)
        self.fleet.capacity = self.capacity
        capacity_obs.register_plane(self.capacity)
        # Node-failure recovery plane: liveness verdicts + automatic
        # evacuation. Constructed here so the /recovery routes and the
        # loop share one controller; the background loop only runs
        # after an explicit recovery.start() (master/main.py) — tests
        # drive check_once()/evacuate() directly.
        from gpumounter_tpu.recovery import RecoveryController
        self.recovery = RecoveryController(
            kube, self.registry, self._client_factory, cfg=self.cfg,
            store=self.store, shards=self.shards, elastic=self.elastic,
            migrations=self.migrations, apihealth=self.apihealth)
        # Gray-failure health plane (gpumounter_tpu/health/): scores
        # every fleet collection pass for the limping node recovery
        # cannot see and quarantines it softly. load() restores the
        # quarantine set a previous master persisted (shard-takeover
        # continuity). The canary prober loop only runs after an
        # explicit canary.start() (master/main.py) — tests drive
        # probe_once() directly. Recovery learns the plane so
        # quarantined != dead (its evacuation rules are untouched; it
        # only reports the flag and retires our record on evacuation).
        from gpumounter_tpu.health import CanaryProber, HealthPlane
        self.health = HealthPlane(self.cfg, recovery=self.recovery,
                                  store=self.store)
        self.health.load()
        self.fleet.health = self.health
        self.recovery.health = self.health
        self.canary = CanaryProber(self.health, self.registry,
                                   self._client_factory, cfg=self.cfg)
        # ICI defragmenter (gpumounter_tpu/defrag/): plans minimal-cost
        # migration sequences off the capacity plane's fragmentation
        # verdicts and drives them through the migration machine with
        # the checkpoint-assisted drain. The background loop only runs
        # after an explicit defrag.start() (master/main.py, opt-in via
        # TPUMOUNTER_DEFRAG) — the /defrag routes drive plan()/run()
        # directly.
        from gpumounter_tpu.defrag import DefragController
        self.defrag = DefragController(
            kube, self.migrations, self.capacity, self.fleet,
            slo=self.slo, apihealth=self.apihealth, shards=self.shards,
            cfg=self.cfg, health=self.health)
        # Fractional chip shares (gpumounter_tpu/vchip/): the master's
        # share books plus the co-location admission controller behind
        # GET/POST /shares. The capacity plane gets the registry so
        # /capacity reports fractional free capacity next to the
        # whole-chip numbers.
        from gpumounter_tpu.vchip.packer import SharePacker
        from gpumounter_tpu.vchip.shares import ShareRegistry
        self.shares = ShareRegistry(cfg=self.cfg)
        self.packer = SharePacker(self.shares, cfg=self.cfg)
        self.capacity.shares = self.shares
        # Closed-loop autoscaler (gpumounter_tpu/autoscale/): fits the
        # per-tenant batch->tokens/sec curve from the fleet's /tenants
        # telemetry and converts queue/throughput trends into gated
        # grow/shrink decisions on elastic intents. The throughput
        # model also rides every fleet collect pass (the capacity/
        # health observer contract) so the curve keeps learning even
        # when the decision loop is off. The background loop only runs
        # after an explicit autoscale.start() (master/main.py, opt-in
        # via TPUMOUNTER_AUTOSCALE) — GET /autoscale and the pause/
        # resume verbs work either way.
        from gpumounter_tpu.autoscale import AutoscaleController
        self.autoscale = AutoscaleController(
            self.elastic, self.capacity, self.fleet, slo=self.slo,
            apihealth=self.apihealth, health=self.health,
            defrag=self.defrag, cfg=self.cfg)
        self.fleet.autoscale_model = self.autoscale.model
        # Flight recorder (obs/flight.py): root/error spans, audit
        # records and ApiHealth transitions of this replica feed the
        # /timeline pane. Idempotent — any number of apps/tests share
        # the process-global recorder.
        from gpumounter_tpu.obs import flight
        flight.install(apihealth=self.apihealth)

    # --- plumbing ---

    def handle(self, method: str, path: str, body: bytes,
               headers: dict[str, str]
               ) -> tuple[int, str, str, dict[str, str]]:
        """Returns (status, content_type, body, response_headers)."""
        try:
            for m, pattern, name in _ROUTES:
                if m != method:
                    continue
                match = pattern.match(path)
                if match:
                    return self._dispatch(name, match, method, path,
                                          body, headers)
            raise _HttpError(404, "404 page not found")
        except _HttpError as exc:
            return exc.status, "text/plain", exc.message + "\n", exc.headers
        except Exception as exc:  # noqa: BLE001 — boundary
            logger.exception("unhandled error for %s %s", method, path)
            return 500, "text/plain", f"Service Internal Error: {exc}\n", {}

    #: probe/scrape surfaces a cluster hits every few seconds: never
    #: traced — ~14k spans/day of healthz+metrics noise would rotate
    #: the 2048-span ring and evict the mount traces operators actually
    #: query (RUNBOOK "Debugging a slow mount"). /fleet and /slo are
    #: dashboard-polled scrape surfaces of the same kind.
    UNTRACED_ROUTES = frozenset({"index", "healthz", "metrics", "fleet",
                                 "slo", "shards", "recovery", "tenants",
                                 "apihealth", "timeline", "capacity",
                                 "defrag", "shares", "health_nodes",
                                 "autoscale"})

    #: routes that bypass the admission gate: liveness/scrape surfaces
    #: must answer even when the replica is saturated by a mount storm
    #: (a gated /healthz would fail probes exactly when the master is
    #: busiest, turning load into restarts).
    UNGATED_ROUTES = frozenset({"index", "healthz", "metrics"})

    @contextlib.contextmanager
    def _admission(self, name: str, headers: dict[str, str]):
        """Bounded concurrent request processing (master_http_concurrency;
        0 = unbounded). Replica-forwarded work (bulk sub-batches) holds a
        slot of its own gate, never the client gate: forwarded requests
        do only local work, so the two-gate split bounds them without a
        proxy cycle ever waiting on itself.

        When a gate exists and a trace is ambient (the edge span of a
        traced route), the WAIT for a slot gets its own http.admission
        child span — so a saturated replica's queueing shows up as the
        "admission" phase of the assembled critical path
        (obs/assembly.py) instead of vanishing into the edge span."""
        if name in self.UNGATED_ROUTES:
            yield
            return
        forwarded = any(k.lower() == FORWARDED_HEADER
                        for k in headers)
        gate = self._forward_gate if forwarded else self._client_gate
        if gate is None:
            yield
            return
        if trace.current() is not None:
            with trace.span("http.admission", route=name,
                            forwarded=forwarded):
                gate.acquire()
        else:
            gate.acquire()
        try:
            yield
        finally:
            gate.release()

    def _dispatch(self, name: str, match, method: str, path: str,
                  body: bytes, headers: dict[str, str]
                  ) -> tuple[int, str, str, dict[str, str]]:
        """One routed request = one root span. The trace id is minted
        HERE (the HTTP edge) unless the caller supplied a valid
        x-tpumounter-trace header, and is echoed on the response so
        callers can pull the story later (`tpumounter trace <id>`).

        Auth runs BEFORE the span opens: an unauthenticated peer must
        not be able to churn the span ring or — via the inbound trace
        header — inject spans into a victim's trace id. The admission
        gate runs INSIDE the edge span of traced routes (its wait is
        the critical path's "admission" phase); untraced routes gate
        without a span, exactly as before."""
        self._check_auth(name, headers)
        if name in self.UNTRACED_ROUTES:
            with self._admission(name, headers):
                status, ctype, text = getattr(
                    self, f"_route_{name}")(match, body, headers)
            return status, ctype, text, {}
        inbound = next((v for k, v in headers.items()
                        if k.lower() == trace.TRACE_HEADER), None)
        extra: dict[str, str] = {}
        # Exceptions are caught OUTSIDE the span so the root http.<name>
        # span closes with status=error — a 500 whose edge span read
        # "ok" would misreport the failure to `tpumounter trace <id>`.
        try:
            with trace.span(f"http.{name}", wire_parent=inbound,
                            http_method=method) as ctx:
                extra = {trace.RESPONSE_HEADER: ctx.trace_id}
                with self._admission(name, headers):
                    if name in self.AUDITED_ROUTES:
                        status, ctype, text = self._audited_route(
                            name, match, body, headers)
                    else:
                        status, ctype, text = getattr(
                            self, f"_route_{name}")(match, body, headers)
                return status, ctype, text, extra
        except _HttpError as exc:
            exc.headers = {**extra, **exc.headers}
            raise
        except Exception as exc:  # noqa: BLE001 — keep the header
            logger.exception("unhandled error for %s %s", method, path)
            return (500, "text/plain",
                    f"Service Internal Error: {exc}\n", extra)

    def _audited_route(self, name: str, match, body: bytes,
                       headers: dict[str, str]) -> tuple[int, str, str]:
        """Every mutating route leaves an audit record: actor (the
        optional x-tpumounter-actor header, else "http"), the pod when
        the route names one, the HTTP outcome, duration, and the edge
        trace id. Worker-side records add the chip set for the same
        trace."""
        groups = match.groupdict()
        actor = next((v for k, v in headers.items()
                      if k.lower() == "x-tpumounter-actor"), "") or "http"
        with audited(f"http.{name}", actor=actor,
                     namespace=groups.get("ns", ""),
                     pod=groups.get("pod", "")) as rec:
            try:
                status, ctype, text = getattr(
                    self, f"_route_{name}")(match, body, headers)
            except _HttpError as exc:
                rec["outcome"] = f"http {exc.status}"
                raise
            rec["outcome"] = f"http {status}"
            return status, ctype, text

    def _check_auth(self, route_name: str, headers: dict[str, str]) -> None:
        if route_name in self.UNAUTHENTICATED_ROUTES:
            return
        from gpumounter_tpu.utils.auth import check_bearer
        value = next((v for k, v in headers.items()
                      if k.lower() == "authorization"), None)
        if route_name in self.READ_ROUTES:
            if self._read_token is not None:
                # Distinct read scope: the read token or the mutate
                # token (mutate implies read) — nothing else.
                if check_bearer(value, self._read_token) or (
                        self._token is not None
                        and check_bearer(value, self._token)):
                    return
                logger.warning("unauthorized %s read rejected", route_name)
                raise _HttpError(
                    401, "missing or invalid bearer token (read scope)")
            if route_name == "metrics":
                return  # legacy open scrape surface (probes/scrapers)
            # /audit and /trace reveal pod names and chip movements:
            # without a read token they require the mutate token below.
        if self._token is None:
            return
        if not check_bearer(value, self._token):
            logger.warning("unauthenticated %s request rejected", route_name)
            raise _HttpError(401, "missing or invalid bearer token")

    def _shard_gate(self, node: str, path: str) -> None:
        """Sharded masters: a request for a node another replica owns is
        307-redirected to the owner's advertised URL (clients follow —
        rpc/http_failover.py); an ownerless shard (lease expired, the
        renew loops racing to claim it) answers 503 + Retry-After."""
        kind, url = self.shards.route(node)
        if kind == "local":
            return
        if kind == "remote" and url:
            raise _HttpError(
                307, f"node {node} is owned by master replica at {url}",
                headers={"Location": url.rstrip("/") + path})
        raise _HttpError(
            503, f"shard for node {node} has no live owner yet; retry",
            headers={"Retry-After": "1"})

    def _worker_for_pod(self, namespace: str, pod_name: str,
                        redirect_path: str | None = None
                        ) -> tuple[str, str]:
        """(worker_address, node_name); raises _HttpError on miss. With
        redirect_path set, non-owned nodes 307 to their shard owner
        before any worker lookup happens here. The pod fetch gets a
        k8s.get_pod span: API-server wait is its own phase of the
        assembled critical path (obs/assembly.py)."""
        try:
            with trace.span("k8s.get_pod", pod=f"{namespace}/{pod_name}"):
                pod = Pod(self.kube.get_pod(namespace, pod_name))
        except NotFoundError:
            raise _HttpError(
                404, f"No pod: {pod_name} in namespace: {namespace}")
        node = pod.node_name
        if not node:
            raise _HttpError(400, f"Pod {pod_name} is not scheduled yet")
        if redirect_path is not None:
            self._shard_gate(node, redirect_path)
        address = self.registry.worker_address(node)
        if address is None:
            logger.error("no tpumounter worker on node %s", node)
            raise _HttpError(500, "Service Internal Error")
        # Degraded worker: answer 503 + Retry-After immediately instead of
        # queueing the request behind a dial that is known to hang. Pure
        # view (retry_after, not allow) so the route never consumes the
        # breaker's single half-open probe slot — the actual RPC does.
        retry_after = self.registry.breaker.retry_after(address)
        if retry_after is not None:
            raise _HttpError(
                503,
                f"worker on node {node} is degraded (circuit breaker "
                f"open); retry in {retry_after:.0f}s",
                headers={"Retry-After": str(max(1, int(retry_after + 0.5)))})
        return address, node

    # --- routes ---

    def _route_index(self, match, body, headers):
        return 200, "text/plain", "tpumounter master\n"

    def _route_healthz(self, match, body, headers):
        # Liveness stays 200 through an API outage — restarting the
        # master is exactly the wrong reflex then (it would dump the
        # read cache and the in-memory half of the degraded state); the
        # verdict rides in the body for operators and the CLI.
        state = self.apihealth.state()
        if state == "healthy":
            return 200, "text/plain", "ok\n"
        return 200, "text/plain", f"ok\napi: {state}\n"

    def _route_apihealth(self, match, body, headers):
        """The degraded-mode pane: ApiHealth state machine verdict +
        the store's cache staleness stamps + write-behind queue books
        (see `tpumounter apihealth` and the RUNBOOK walkthrough)."""
        import json as jsonlib
        payload = {"api": self.apihealth.payload()}
        store_payload = getattr(self.store, "payload", None)
        if callable(store_payload):
            payload["store"] = store_payload()
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _route_metrics(self, match, body, headers):
        accept = next((v for k, v in headers.items()
                       if k.lower() == "accept"), "")
        if "application/openmetrics-text" in accept:
            # OpenMetrics negotiation: histogram bucket lines carry
            # their trace-id exemplars (utils/metrics.py) — the join
            # from a latency outlier to `tpumounter trace <id>`.
            return (200, "application/openmetrics-text; version=1.0.0",
                    REGISTRY.render(openmetrics=True))
        return 200, "text/plain; version=0.0.4", REGISTRY.render()

    def _route_fleet(self, match, body, headers):
        """The federated fleet rollup: per-node mount p50/p95, warm-pool
        hit rate, breaker state, device-access telemetry — collected on
        demand when the cached rollup is older than the scrape
        interval."""
        import json as jsonlib
        payload = self.fleet.payload(
            max_age_s=self.cfg.fleet_scrape_interval_s)
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _route_slo(self, match, body, headers):
        """SLO burn-rate evaluation over the fleet rollup. Refreshes the
        rollup first when stale so the burn numbers describe now, not
        the last background pass (refresh only — building the full
        fleet payload here would be discarded work)."""
        import json as jsonlib
        self.fleet.refresh_if_stale(self.cfg.fleet_scrape_interval_s)
        return 200, "application/json", \
            jsonlib.dumps(self.slo.payload(), indent=1) + "\n"

    def _route_capacity(self, match, body, headers):
        """The capacity & fragmentation pane: per-host and fleet ICI
        fragmentation indices, the per-size allocation-feasibility
        table (blocking hosts named) and the headroom forecast —
        collected on demand when the rollup is stale, federated
        per-shard exactly like /fleet. ?accel_type= filters the
        feasibility table to one accelerator type (404 on an unknown
        one)."""
        import json as jsonlib
        params = urllib.parse.parse_qs(match.group("query") or "")
        accel = params.get("accel_type", [None])[-1]
        try:
            payload = self.capacity.payload(
                max_age_s=self.cfg.fleet_scrape_interval_s,
                accel_type=accel)
        except KeyError:
            # Only the ?accel_type= filter raises KeyError by contract;
            # an internal KeyError on an unfiltered read must stay a
            # 500 (a server bug must not masquerade as a client error).
            if accel is None:
                raise
            raise _HttpError(
                404, f"unknown accelerator type {accel!r}; see "
                     f"master/topology.py for the known shapes")
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _route_tenants(self, match, body, headers):
        """The per-tenant disruption ledger: what each tenant's training
        loop experienced (step rate, tokens/sec, queue depth) and every
        disruption window attributed to its cause, joined against the
        trace plane (each window's trace id links to /trace/<id>)."""
        import json as jsonlib
        payload = self.fleet.tenants_payload(
            max_age_s=self.cfg.fleet_scrape_interval_s)
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _route_recovery(self, match, body, headers):
        """The recovery plane's state: per-node liveness verdicts, the
        evacuation history, and the controller's confirmation config —
        the 'verify' step of the RUNBOOK's node-failure walkthrough."""
        import json as jsonlib
        return 200, "application/json", \
            jsonlib.dumps(self.recovery.payload(), indent=1) + "\n"

    def _route_recovery_evacuate(self, match, body, headers):
        """Manual evacuation: an operator who confirmed a node death
        out-of-band (console says the VM is gone) can skip the
        confirmation window. Shard-gated like every per-node mutation —
        the node's owner runs the evacuation."""
        import json as jsonlib
        node = match.group("node")
        self._shard_gate(node, f"/recovery/evacuate/{node}")
        record = self.recovery.evacuate(node, reason="manual")
        return 200, "application/json", \
            jsonlib.dumps(record, indent=1) + "\n"

    def _route_health_nodes(self, match, body, headers):
        """The gray-failure plane's pane: per-node quarantine state
        machine (state / signals / canary streaks / drain
        recommendation — node names ride HERE, never metric labels),
        the fleet quarantine budget, and the last scoring pass's
        verdict. The 'health' step of the RUNBOOK's limping-node
        walkthrough."""
        import json as jsonlib
        return 200, "application/json", \
            jsonlib.dumps(self.health.payload(), indent=1) + "\n"

    def _route_health_quarantine(self, match, body, headers):
        """Manual quarantine/release (body {"action": "quarantine" |
        "release", "reason": ...}; default quarantine). Shard-gated
        like every per-node mutation. Quarantine is soft — nothing is
        unmounted — so unlike /recovery/evacuate there is no
        confirmation window to skip; release REFUSES a node the
        recovery plane evacuated (resurrection is not a release)."""
        import json as jsonlib
        node = match.group("node")
        self._shard_gate(node, f"/health/quarantine/{node}")
        try:
            req = jsonlib.loads(body.decode() or "{}")
        except ValueError:
            raise _HttpError(400, "body is not valid JSON")
        if not isinstance(req, dict):
            raise _HttpError(400, "body must be a JSON object")
        action = str(req.get("action") or "quarantine")
        actor = headers.get("x-tpumounter-actor", "http")
        try:
            if action == "quarantine":
                pane = self.health.quarantine(
                    node, reason=str(req.get("reason") or ""),
                    actor=actor)
            elif action == "release":
                pane = self.health.release(node, actor=actor)
            else:
                raise _HttpError(
                    400, f"unknown action {action!r} "
                         "(quarantine or release)")
        except ValueError as exc:
            raise _HttpError(409, str(exc))
        return 200, "application/json", \
            jsonlib.dumps({"node": node, "action": action,
                           "health": pane}, indent=1) + "\n"

    def _route_defrag(self, match, body, headers):
        """The defragmenter's state pane: gate verdicts (ApiHealth +
        SLO burn), the adopted plan, the in-flight run with its barrier
        fragmentation samples, and recent run history — the RUNBOOK's
        'Recovering capacity with the defragmenter' walkthrough reads
        this between every step."""
        import json as jsonlib
        return 200, "application/json", \
            jsonlib.dumps(self.defrag.payload(), indent=1) + "\n"

    def _defrag_call(self, fn, *args, **kwargs):
        """Shared refusal mapping: a DefragRefused carries its own HTTP
        status (409 stale/no-plan/busy, 503 parked) — the 503s get a
        Retry-After so operator scripts back off instead of spinning."""
        from gpumounter_tpu.defrag import DefragRefused
        try:
            return fn(*args, **kwargs)
        except DefragRefused as exc:
            headers = {}
            # DefragRefused is our own HTTP refusal type, not a k8s API
            # error — .status IS the response code it asks for.
            if exc.status == 503:  # tpulint: allow[typed-k8s-errors] own HTTP type
                headers["Retry-After"] = str(
                    int(self.cfg.defrag_interval_s))
            raise _HttpError(exc.status, str(exc), headers=headers)

    def _route_defrag_plan(self, match, body, headers):
        """Compute and adopt a plan from a fresh capacity snapshot.
        Optional JSON body: {"target_block": N} overrides the
        configured defrag_target_block for this plan only."""
        import json as jsonlib
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _HttpError(400, "body must be a JSON object")
        target = payload.get("target_block")
        if target is not None and (not isinstance(target, int)
                                   or target < 1):
            raise _HttpError(
                400, f"target_block must be a positive integer, "
                     f"got {target!r}")
        plan = self._defrag_call(self.defrag.plan, target_block=target)
        return 200, "application/json", \
            jsonlib.dumps(plan, indent=1) + "\n"

    def _route_defrag_run(self, match, body, headers):
        """Execute the adopted plan on a background thread. Optional
        JSON body: {"plan_id": "dfp-..."} pins the run to a specific
        plan (409 if another plan was adopted since)."""
        import json as jsonlib
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _HttpError(400, "body must be a JSON object")
        out = self._defrag_call(self.defrag.run,
                                plan_id=payload.get("plan_id"))
        return 200, "application/json", \
            jsonlib.dumps(out, indent=1) + "\n"

    def _route_defrag_pause(self, match, body, headers):
        import json as jsonlib
        return 200, "application/json", \
            jsonlib.dumps(self.defrag.pause(), indent=1) + "\n"

    def _route_autoscale(self, match, body, headers):
        """The autoscaler's state pane: gate verdicts (ApiHealth +
        tenant-SLO burn + pause), the throughput model's per-tenant
        fits with their refusal verdicts, the last evaluate pass and
        recent grow/shrink decisions — the RUNBOOK's 'Reading and
        pausing the autoscaler' walkthrough reads this between every
        step."""
        import json as jsonlib
        return 200, "application/json", \
            jsonlib.dumps(self.autoscale.payload(), indent=1) + "\n"

    def _autoscale_call(self, fn, *args, **kwargs):
        """Refusal mapping, the _defrag_call shape: an AutoscaleRefused
        carries its own HTTP status (409 paused/busy, 503 parked) —
        the 503s get a Retry-After so operator scripts back off."""
        from gpumounter_tpu.autoscale import AutoscaleRefused
        try:
            return fn(*args, **kwargs)
        except AutoscaleRefused as exc:
            headers = {}
            # AutoscaleRefused is our own HTTP refusal type, not a k8s
            # API error — .status IS the response code it asks for.
            if exc.status == 503:  # tpulint: allow[typed-k8s-errors] own HTTP type
                headers["Retry-After"] = str(
                    int(self.cfg.autoscale_interval_s))
            raise _HttpError(exc.status, str(exc), headers=headers)

    def _route_autoscale_pause(self, match, body, headers):
        import json as jsonlib
        actor = headers.get("x-tpumounter-actor", "http")
        return 200, "application/json", \
            jsonlib.dumps(self.autoscale.pause(actor=actor),
                          indent=1) + "\n"

    def _route_autoscale_resume(self, match, body, headers):
        import json as jsonlib
        actor = headers.get("x-tpumounter-actor", "http")
        return 200, "application/json", \
            jsonlib.dumps(self.autoscale.resume(actor=actor),
                          indent=1) + "\n"

    def _route_autoscale_evaluate(self, match, body, headers):
        """Run one evaluate pass now instead of waiting for the
        background interval (the defrag /plan analogue). Refusals —
        paused (409), SLO burn or degraded API (503 + Retry-After) —
        map through _autoscale_call; nothing fires through a closed
        gate."""
        import json as jsonlib
        out = self._autoscale_call(self.autoscale.evaluate_once)
        return 200, "application/json", \
            jsonlib.dumps(out, indent=1) + "\n"

    def _route_shares(self, match, body, headers):
        """The fractional share books: every (tenant, chip, weight,
        rate budget) share, per-chip load/headroom, and the co-location
        totals — the read half of the RUNBOOK's 'Co-locating tenants on
        shared chips' walkthrough."""
        import json as jsonlib
        return 200, "application/json", \
            jsonlib.dumps(self.shares.payload(), indent=1) + "\n"

    def _route_shares_admit(self, match, body, headers):
        """Admit a tenant onto fractional shares. JSON body:
        {"namespace","pod","profile","chips",N,"weight",W,
         "rate_budget":B?, "inventory":{chip_uuid:node}?}. The packer
        prefers already-shared chips with a complementary profile, then
        free chips off the defragmenter's blocked hosts; a typed
        refusal maps to 409 (never a silent partial booking)."""
        import json as jsonlib
        from gpumounter_tpu.vchip.packer import PackRefused
        from gpumounter_tpu.vchip.shares import ShareLimitError
        if not self.cfg.vchip_enabled:
            raise _HttpError(503, "fractional shares are disabled "
                                  "(TPUMOUNTER_VCHIP=false)")
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _HttpError(400, "body must be a JSON object")
        namespace = payload.get("namespace")
        pod = payload.get("pod")
        if not namespace or not pod:
            raise _HttpError(400, "namespace and pod are required")
        inventory = payload.get("inventory") or {}
        if not isinstance(inventory, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in inventory.items()):
            raise _HttpError(
                400, "inventory must map chip uuid -> node name")
        try:
            chips = int(payload.get("chips", 1))
            weight = int(payload.get("weight", 0))
            rate_budget = int(payload.get("rate_budget", 0))
        except (TypeError, ValueError):
            raise _HttpError(
                400, "chips, weight and rate_budget must be integers")
        try:
            booked = self.packer.admit(
                str(namespace), str(pod),
                str(payload.get("profile", "balanced")), chips, weight,
                rate_budget=rate_budget, inventory=inventory,
                blocked_hosts=self.capacity.blocked_hosts(
                    max_age_s=self.cfg.fleet_scrape_interval_s),
                # Quarantined hosts are a HARD exclusion (unlike the
                # defragmenter's last-resort blocked_hosts): no new
                # work lands on a limping node. Probation hosts stay
                # placeable but rank last.
                excluded_hosts=self.health.excluded_hosts(),
                probation_hosts=self.health.probation_hosts())
        except (PackRefused, ShareLimitError) as exc:
            # Typed admission refusals carry their own story; 409 tells
            # scripted callers "the fleet, not your request, said no".
            raise _HttpError(409, str(exc))  # tpulint: allow[typed-k8s-errors] own HTTP type
        return 200, "application/json", jsonlib.dumps({
            "admitted": [s.to_json() for s in booked],
        }, indent=1) + "\n"

    def _route_shares_release(self, match, body, headers):
        """Release every share a tenant holds (DELETE
        /shares/<ns>/<pod>); 404 when the tenant holds none."""
        import json as jsonlib
        ns, pod = match.group("ns"), match.group("pod")
        released = self.packer.release(ns, pod)
        if not released:
            raise _HttpError(404, f"{ns}/{pod} holds no shares")
        return 200, "application/json", jsonlib.dumps({
            "released": [s.to_json() for s in released],
        }, indent=1) + "\n"

    def _route_audit(self, match, body, headers):
        """Query the append-only audit trail. Filters (all optional):
        ?namespace= &pod= &op= (prefix) &trace= &outcome= (prefix)
        &limit= (default 100). The query contract lives in
        obs.audit.query_from_params, shared with the worker ops port."""
        import json as jsonlib
        params = urllib.parse.parse_qs(match.group("query") or "")
        from gpumounter_tpu.obs.audit import query_from_params
        try:
            payload = query_from_params(params)
        except ValueError:
            raise _HttpError(400, f"Invalid limit: {params.get('limit')!r}")
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _route_trace(self, match, body, headers):
        """The assembled end-to-end story for one trace id: master
        spans joined with the worker spans the fleet collector
        federated (obs/assembly.py), rendered as a waterfall with
        per-phase critical-path attribution and a completeness verdict.
        An incomplete assembly triggers ONE bounded fleet refresh (the
        missing worker half may simply not have been scraped yet)
        before answering."""
        import json as jsonlib

        from gpumounter_tpu.obs import assembly
        tid = match.group("tid")
        payload = assembly.assemble(tid)
        if payload is not None and not payload["complete"]:
            # Pull fresh worker rings once (single-flight, 1 s floor so
            # a polling dashboard cannot turn incomplete traces into a
            # scrape storm), then re-join.
            try:
                self.fleet.refresh_if_stale(max_age_s=1.0)
            except Exception:  # noqa: BLE001 — the join still answers
                logger.exception("fleet refresh for /trace/%s failed", tid)
            payload = assembly.assemble(tid)
        if payload is None:
            raise _HttpError(
                404, f"no spans buffered for trace {tid} (expired from "
                     f"the ring, or minted elsewhere)")
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _route_timeline(self, match, body, headers):
        """The incident flight recorder's merged chronological
        timeline (obs/flight.py). Filters (all optional): ?node= &trace=
        &kind= (span/audit/event/apihealth/recovery/marker) &from= &to=
        (unix seconds) &limit= (default 500, newest win)."""
        import json as jsonlib

        from gpumounter_tpu.obs.flight import query_from_params
        params = urllib.parse.parse_qs(match.group("query") or "")
        try:
            payload = query_from_params(params)
        except ValueError:
            raise _HttpError(
                400, f"Invalid timeline filter: {params!r} (from/to/"
                     f"limit must be numeric)")
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _parse_slice_body(self, body: bytes):
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import SliceTarget
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _HttpError(400, 'body must be a JSON object with a '
                                  '"pods" list')
        raw = payload.get("pods")
        if not isinstance(raw, list) or not raw:
            raise _HttpError(400, 'JSON body needs "pods": '
                                  '[{"namespace": ..., "pod": ...}, ...]')
        targets = []
        for entry in raw:
            if not isinstance(entry, dict):
                raise _HttpError(400, f"pods entries must be objects "
                                      f'{{"namespace", "pod"}}: {entry!r}')
            ns = entry.get("namespace", "default")
            pod = entry.get("pod")
            if not pod:
                raise _HttpError(400, f"pods entry missing 'pod': {entry}")
            targets.append(SliceTarget(namespace=ns, pod=pod))
        return payload, targets

    def _slice_coordinator(self):
        from gpumounter_tpu.master.slice_ops import SliceCoordinator
        return SliceCoordinator(self.kube, self.registry,
                                self._client_factory, self.cfg,
                                shards=self.shards)

    def _route_addslice(self, match, body, headers):
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import SliceError
        payload, targets = self._parse_slice_body(body)
        try:
            chips = int(payload.get("chipsPerHost", 4))
        except (TypeError, ValueError):
            raise _HttpError(400, f"Invalid chipsPerHost: "
                                  f"{payload.get('chipsPerHost')!r}")
        if chips <= 0:
            raise _HttpError(400, f"Invalid chipsPerHost: {chips}")
        entire = bool(payload.get("isEntireMount", True))
        accel_type = payload.get("acceleratorType") or None
        topology_hint = payload.get("topology") or None
        prefer_ici = bool(payload.get("preferIci", False))
        try:
            plan = self._slice_coordinator().mount_slice(
                targets, chips, entire, accel_type=accel_type,
                topology_hint=topology_hint, prefer_ici=prefer_ici)
        except SliceError as exc:
            raise _HttpError(exc.status, str(exc),
                             headers=_slice_headers(exc))
        return 200, "application/json", jsonlib.dumps(plan, indent=1) + "\n"

    def _route_removeslice(self, match, body, headers):
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import SliceError
        payload, targets = self._parse_slice_body(body)
        force = bool(payload.get("force", False))
        try:
            outcome = self._slice_coordinator().remove_slice(targets, force)
        except SliceError as exc:
            raise _HttpError(exc.status, str(exc),
                             headers=_slice_headers(exc))
        return 200, "application/json", jsonlib.dumps(outcome) + "\n"

    def _route_workers(self, match, body, headers):
        # Worker registry endpoint (no reference analog): node → worker IP.
        lines = [f"{node} {ip}" for node, ip in
                 sorted(self.registry.registry_snapshot().items())]
        return 200, "text/plain", "\n".join(lines) + "\n"

    def _route_shards(self, match, body, headers):
        import json as jsonlib
        return 200, "application/json", \
            jsonlib.dumps(self.shards.table(), indent=1) + "\n"

    # --- bulk mount (POST /batch/addtpu) ---

    def _parse_bulk_body(self, body: bytes):
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import BulkTarget
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _HttpError(400, 'body must be a JSON object with a '
                                  '"targets" list')
        raw = payload.get("targets")
        if not isinstance(raw, list) or not raw:
            raise _HttpError(400, 'JSON body needs "targets": '
                                  '[{"namespace", "pod", "chips", '
                                  '"isEntireMount"}, ...]')
        if len(raw) > self.cfg.bulk_max_targets:
            raise _HttpError(
                400, f"too many targets: {len(raw)} > "
                     f"{self.cfg.bulk_max_targets} (BULK_MAX_TARGETS)")
        targets = []
        for entry in raw:
            if not isinstance(entry, dict) or not entry.get("pod"):
                raise _HttpError(400, f"targets entries must be objects "
                                      f"with a 'pod': {entry!r}")
            try:
                chips = int(entry.get("chips", 1))
            except (TypeError, ValueError):
                raise _HttpError(400, f"invalid chips for "
                                      f"{entry.get('pod')}: "
                                      f"{entry.get('chips')!r}")
            if not 0 < chips <= self.cfg.max_tpu_per_request:
                raise _HttpError(
                    400, f"invalid chips {chips} for {entry['pod']} "
                         f"(must be 1..{self.cfg.max_tpu_per_request})")
            targets.append(BulkTarget(
                namespace=entry.get("namespace", "default"),
                pod=entry["pod"], chips=chips,
                entire=bool(entry.get("isEntireMount", False))))
        return targets

    def _route_batch_add(self, match, body, headers):
        """One request -> many pod/chip mounts. Targets are grouped by
        owning shard: local shards mount here (grouped by node over the
        pooled channels — slice_ops.BulkMountCoordinator), peer-owned
        shards have their sub-batch proxied to the owner, and every
        target gets an individual result — a bad pod or a dead shard
        never fails the rest of the batch."""
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import BulkMountCoordinator
        targets = self._parse_bulk_body(body)
        forwarded = any(k.lower() == FORWARDED_HEADER for k in headers)
        coordinator = BulkMountCoordinator(
            self.kube, self.registry, self._client_factory, self.cfg,
            shards=self.shards)
        results: list[dict | None] = [None] * len(targets)
        resolve_errors, by_node = coordinator._resolve_bulk(targets)
        for i, err in resolve_errors.items():
            results[i] = {"namespace": targets[i].namespace,
                          "pod": targets[i].pod, **err}
        local_by_node: dict[str, list[int]] = {}
        remote: dict[str, list[int]] = {}
        for node, indices in by_node.items():
            kind, url = self.shards.route(node)
            if kind == "local":
                local_by_node[node] = indices
            elif forwarded:
                # Never a second hop: the proxying replica believed we
                # owned this node; if ownership moved meanwhile the
                # client retries against fresh routing.
                for i in indices:
                    results[i] = {
                        "namespace": targets[i].namespace,
                        "pod": targets[i].pod, "node": node,
                        "result": "NotOwner",
                        "error": f"replica does not own node {node}"}
            elif kind == "remote" and url:
                remote.setdefault(url, []).extend(indices)
            else:
                for i in indices:
                    results[i] = {
                        "namespace": targets[i].namespace,
                        "pod": targets[i].pod, "node": node,
                        "result": "Unowned", "retryAfterS": 1,
                        "error": f"shard for node {node} has no live "
                                 f"owner yet"}

        forwards = []
        if remote:
            # Contextvars don't cross threads: capture the edge span's
            # context HERE and re-attach it in each forwarder, so the
            # X-Tpumounter-Trace header _proxy_batch stamps carries THIS
            # request's trace — the owner replica joins the forwarding
            # replica's trace instead of minting a fresh root (which
            # orphaned the remote half of every proxied bulk mount).
            edge_ctx = trace.current()

            def _forward(item: tuple[str, list[int]]) -> None:
                url, indices = item
                with trace.attached(edge_ctx), \
                        trace.span("proxy.batch", url=url,
                                   targets=len(indices)):
                    entries = self._proxy_batch(
                        url, [targets[i] for i in indices])
                for i, entry in zip(indices, entries):
                    results[i] = entry

            # Futures on the shared core, NOT a blocking core.run():
            # the remote sub-batches must overlap with the local mounts
            # below (the old thread-per-URL behavior). _forward is
            # exception-safe (_proxy_batch returns ProxyError entries).
            from gpumounter_tpu.utils.fanout import get_core
            core = get_core(self.cfg)
            forwards = [core.submit(_forward, item, kind="batch-proxy")
                        for item in remote.items()]
        if local_by_node:
            # One resolve total: the grouping computed above IS the
            # mount plan (re-resolving would double the pod reads and
            # let a rescheduled pod dodge the shard routing decision).
            local_results = coordinator.mount_bulk(
                targets, resolution=({}, local_by_node))
            for indices in local_by_node.values():
                for i in indices:
                    results[i] = local_results[i]
        for fut in forwards:
            fut.result()

        out = [r if r is not None else
               {"namespace": targets[i].namespace, "pod": targets[i].pod,
                "result": "Error", "error": "internal: unprocessed"}
               for i, r in enumerate(results)]
        by_result: dict[str, int] = {}
        for entry in out:
            by_result[entry.get("result", "Error")] = \
                by_result.get(entry.get("result", "Error"), 0) + 1
        payload = {
            "results": out,
            "summary": {"total": len(out),
                        "success": by_result.get("Success", 0),
                        "byResult": by_result},
        }
        return 200, "application/json", \
            jsonlib.dumps(payload, indent=1) + "\n"

    def _proxy_batch(self, url: str, sub_targets) -> list[dict]:
        """POST a sub-batch to the owning replica; per-target entries
        come back in order. A transport failure becomes per-target
        ProxyError entries — never an exception out of the route."""
        import json as jsonlib
        import urllib.error
        import urllib.request
        payload = {"targets": [
            {"namespace": t.namespace, "pod": t.pod, "chips": t.chips,
             "isEntireMount": t.entire} for t in sub_targets]}
        request_headers = {
            "Content-Type": "application/json",
            FORWARDED_HEADER: "1",
            # The peer's worker-side spans should join THIS request's
            # trace, exactly like a locally-mounted target's do.
            trace.TRACE_HEADER: trace.wire_context(),
        }
        if self._token:
            request_headers["Authorization"] = f"Bearer {self._token}"
        req = urllib.request.Request(
            url.rstrip("/") + "/batch/addtpu",
            data=jsonlib.dumps(payload).encode(), method="POST",
            headers=request_headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=self.cfg.bulk_proxy_timeout_s) as resp:
                answered = jsonlib.loads(resp.read().decode())
            entries = answered.get("results", [])
            if len(entries) != len(sub_targets):
                raise ValueError(
                    f"peer answered {len(entries)} results for "
                    f"{len(sub_targets)} targets")
            return entries
        except Exception as exc:  # noqa: BLE001 — peer/transport boundary
            logger.error("bulk proxy to %s failed: %s", url, exc)
            return [{"namespace": t.namespace, "pod": t.pod,
                     "result": "ProxyError",
                     "error": f"owner replica {url} unreachable: {exc}"}
                    for t in sub_targets]

    # --- elastic intents ---

    def _intent_status(self, ns: str, pod: str, intent) -> dict:
        entry = {"namespace": ns, "pod": pod, **intent.to_json()}
        status = self.elastic.status_for(ns, pod)
        if status is not None:
            entry["status"] = status
        return entry

    def _route_intents_list(self, match, body, headers):
        import json as jsonlib
        items = [self._intent_status(ns, pod, intent)
                 for ns, pod, intent in self.elastic.store.list()]
        return 200, "application/json", \
            jsonlib.dumps({"intents": items}, indent=1) + "\n"

    def _route_intent_get(self, match, body, headers):
        import json as jsonlib
        ns, pod = match.group("ns"), match.group("pod")
        try:
            intent = self.elastic.store.get(ns, pod)
        except NotFoundError:
            raise _HttpError(404, f"No pod: {pod} in namespace: {ns}")
        if intent is None:
            raise _HttpError(404, f"no intent declared for {ns}/{pod}")
        return 200, "application/json", \
            jsonlib.dumps(self._intent_status(ns, pod, intent),
                          indent=1) + "\n"

    def _route_intent_put(self, match, body, headers):
        import json as jsonlib

        from gpumounter_tpu.elastic import Intent, IntentError
        ns, pod = match.group("ns"), match.group("pod")
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        try:
            intent = Intent.from_json(payload)
            self.elastic.store.put(ns, pod, intent)
        except IntentError as exc:
            raise _HttpError(400, str(exc))
        except NotFoundError:
            raise _HttpError(404, f"No pod: {pod} in namespace: {ns}")
        logger.info("intent declared: %s/%s -> %s", ns, pod,
                    intent.to_json())
        self.elastic.enqueue(ns, pod, priority=intent.priority)
        return 200, "application/json", \
            jsonlib.dumps(self._intent_status(ns, pod, intent),
                          indent=1) + "\n"

    def _route_intent_delete(self, match, body, headers):
        import json as jsonlib
        ns, pod = match.group("ns"), match.group("pod")
        try:
            had = self.elastic.store.delete(ns, pod)
        except NotFoundError:
            raise _HttpError(404, f"No pod: {pod} in namespace: {ns}")
        return 200, "application/json", \
            jsonlib.dumps({"deleted": had}) + "\n"

    # --- live migration ---

    def _route_migrate_start(self, match, body, headers):
        import json as jsonlib

        from gpumounter_tpu.migrate import MigrationError
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _HttpError(400, 'body must be a JSON object with '
                                  '"source" and "destination"')

        def _ref(key):
            entry = payload.get(key)
            if not isinstance(entry, dict) or not entry.get("pod"):
                raise _HttpError(
                    400, f'"{key}" must be {{"namespace": ..., '
                         f'"pod": ...}}')
            return entry.get("namespace", "default"), entry["pod"]

        src_ns, src_pod = _ref("source")
        dst_ns, dst_pod = _ref("destination")
        checkpoint = payload.get("checkpoint", False)
        if not isinstance(checkpoint, bool):
            raise _HttpError(400, '"checkpoint" must be a boolean')
        try:
            journal = self.migrations.begin(src_ns, src_pod,
                                            dst_ns, dst_pod,
                                            checkpoint=checkpoint)
        except MigrationError as exc:
            raise _HttpError(exc.status, str(exc))
        return 200, "application/json", \
            jsonlib.dumps(journal, indent=1) + "\n"

    def _route_migrations_list(self, match, body, headers):
        import json as jsonlib
        return 200, "application/json", jsonlib.dumps(
            {"migrations": self.migrations.list_migrations()},
            indent=1) + "\n"

    def _route_migration_get(self, match, body, headers):
        import json as jsonlib
        journal = self.migrations.get(match.group("mid"))
        if journal is None:
            raise _HttpError(404, f"no migration {match.group('mid')}")
        return 200, "application/json", \
            jsonlib.dumps(journal, indent=1) + "\n"

    def _route_migration_abort(self, match, body, headers):
        import json as jsonlib

        from gpumounter_tpu.migrate import MigrationError
        try:
            out = self.migrations.abort(match.group("mid"))
        except MigrationError as exc:
            raise _HttpError(exc.status, str(exc))
        return 200, "application/json", jsonlib.dumps(out) + "\n"

    def _route_add(self, match, body, headers):
        ns = match.group("ns")
        pod_name = match.group("pod")
        num_raw = match.group("num")
        try:
            tpu_num = int(num_raw)
        except ValueError:
            raise _HttpError(400, f"Invalid param gpuNum: {num_raw}")
        if not 0 < tpu_num <= self.cfg.max_tpu_per_request:
            raise _HttpError(
                400, f"Invalid param gpuNum: {num_raw} (must be 1.."
                     f"{self.cfg.max_tpu_per_request})")
        entire = _parse_bool(match.group("entire"), "isEntireMount")
        logger.info("AddTPU request: %s/%s num=%d entire=%s",
                    ns, pod_name, tpu_num, entire)
        address, node = self._worker_for_pod(ns, pod_name,
                                             redirect_path=match.string)
        from gpumounter_tpu.master.shard import epoch_kwargs
        with self._client_factory(address) as client:
            try:
                result = client.add_tpu(pod_name, ns, tpu_num, entire,
                                        **epoch_kwargs(self.shards, node))
            except Exception as exc:  # noqa: BLE001 — gRPC boundary
                logger.error("worker AddTPU failed: %s", exc)
                raise _degraded_or_500(exc)
        if result == api.AddTPUResult.Success:
            return 200, "text/plain", "Add TPU Success\n"
        if result == api.AddTPUResult.InsufficientTPU:
            # Rejected for capacity: stamp the feasibility verdict into
            # the audit trail + flight recorder (obs/capacity.py) so
            # the incident timeline says WHY the intent couldn't place
            # (fragmentation vs exhaustion, blocking numbers).
            self.capacity.record_rejection(node, ns, pod_name, tpu_num)
            raise _HttpError(500, f"Insufficient TPU on Node: {node}")
        if result == api.AddTPUResult.PodNotFound:
            raise _HttpError(400, f"No Pod {pod_name} on Node: {node}")
        raise _HttpError(500, f"unknown worker result {result}")

    def _route_remove(self, match, body, headers):
        ns = match.group("ns")
        pod_name = match.group("pod")
        force = _parse_bool(match.group("force"), "force")
        form = urllib.parse.parse_qs(body.decode("utf-8", "replace"))
        raw_uuids = form.get("uuids")
        if not raw_uuids:
            raise _HttpError(400, "Invalid parameter")
        uuids: list[str] = []
        for entry in raw_uuids:  # repeated fields and comma-joined both work
            uuids.extend(u for u in entry.split(",") if u)
        logger.info("RemoveTPU request: %s/%s uuids=%s force=%s",
                    ns, pod_name, uuids, force)
        address, node = self._worker_for_pod(ns, pod_name,
                                             redirect_path=match.string)
        from gpumounter_tpu.master.shard import epoch_kwargs
        with self._client_factory(address) as client:
            try:
                result = client.remove_tpu(pod_name, ns, uuids, force,
                                           **epoch_kwargs(self.shards,
                                                          node))
            except Exception as exc:  # noqa: BLE001 — gRPC boundary
                logger.error("worker RemoveTPU failed: %s", exc)
                raise _degraded_or_500(exc)
        joined = ", ".join(uuids)
        if result == api.RemoveTPUResult.Success:
            return 200, "text/plain", f"Remove {len(uuids)} TPUs Success\n"
        if result == api.RemoveTPUResult.PodNotFound:
            raise _HttpError(400, f"No Pod {pod_name} on Node: {node}")
        if result == api.RemoveTPUResult.TPUBusy:
            raise _HttpError(
                400, f"Pod: {pod_name} has running processes on TPU: {joined}")
        if result == api.RemoveTPUResult.TPUNotFound:
            raise _HttpError(400, f"Invalid UUIDs: {joined}")
        raise _HttpError(500, f"unknown worker result {result}")


def _grpc_detail(exc: Exception) -> str:
    details = getattr(exc, "details", None)
    if callable(details):
        return str(details())
    return str(exc)


def _slice_headers(exc) -> dict[str, str] | None:
    retry_after = getattr(exc, "retry_after_s", None)
    if retry_after is None:
        return None
    return {"Retry-After": str(max(1, int(retry_after + 0.5)))}


def _degraded_or_500(exc: Exception) -> _HttpError:
    """Map a worker-call failure to HTTP: a breaker that opened (or was
    found open) mid-call is 503 + Retry-After, a fencing rejection is
    503 + Retry-After 1 (this replica's shard view is stale — the
    failover client retries against fresh routing and lands on the
    current owner), anything else 500."""
    from gpumounter_tpu.rpc.resilience import BreakerOpenError, FencedError
    if isinstance(exc, BreakerOpenError):
        return _HttpError(
            503, f"worker degraded (circuit breaker open): {exc}",
            headers={"Retry-After":
                     str(max(1, int(exc.retry_after_s + 0.5)))})
    if isinstance(exc, FencedError):
        return _HttpError(
            503, f"stale shard ownership (fenced by worker): {exc}",
            headers={"Retry-After": "1"})
    return _HttpError(500, f"Service Internal Error: {_grpc_detail(exc)}")


def build_http_server(app: MasterApp, port: int | None = None,
                      host: str = "0.0.0.0") -> ThreadingHTTPServer:
    cfg = app.cfg

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _dispatch(self):
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            status, ctype, text, extra = app.handle(
                self.command, self.path, body, dict(self.headers))
            payload = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            for key, value in extra.items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(payload)

        do_GET = _dispatch
        do_POST = _dispatch
        do_PUT = _dispatch
        do_DELETE = _dispatch

        def log_message(self, fmt, *args):
            logger.debug("http: " + fmt, *args)

    # `is None`, not falsy: port=0 means "ephemeral, kernel-assigned"
    # (the test stacks) — `port or ...` silently rebound it to the
    # config port, colliding with any concurrently-bound master.
    return ThreadingHTTPServer(
        (host, cfg.master_port if port is None else port), Handler)
