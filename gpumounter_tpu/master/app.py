"""Master HTTP gateway.

Reference parity — cmd/GPUMounter-master/main.go:
  * Routes (main.go:232-234):
      GET  /addgpu/namespace/:ns/pod/:pod/gpu/:n/isEntireMount/:bool
      POST /removegpu/namespace/:ns/pod/:pod/force/:bool   (form: uuids)
      GET  /
    plus TPU-native aliases /addtpu/.../tpu/:n/... and /removetpu/...
  * Target pod lookup to find its node (main.go:52-66).
  * Worker discovery by listing labeled pods (findAllWorker, main.go:248-268)
    — but cached with a TTL here instead of one LIST per request
    (SURVEY.md §3 hot-loop fix).
  * gRPC to worker `podIP:1200` (main.go:82,185) via rpc.client.WorkerClient.
  * Result→HTTP mapping kept exactly: Add Success→200 body "Add ... Success",
    Insufficient→500, PodNotFound→400 (main.go:103-116); Remove
    PodNotFound/Busy/NotFound→400, Success→200 (main.go:206-224).

Additions over the reference (SURVEY.md §5 gaps): /healthz, /metrics,
/devices inventory endpoint, structured 404s.
"""

from __future__ import annotations

import re
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from gpumounter_tpu.config import get_config
from gpumounter_tpu.k8s.client import KubeClient, NotFoundError
from gpumounter_tpu.k8s.types import Pod
from gpumounter_tpu.rpc import api
from gpumounter_tpu.rpc.client import WorkerClient
from gpumounter_tpu.utils.log import get_logger
from gpumounter_tpu.utils.metrics import REGISTRY

logger = get_logger("master")


class WorkerRegistry:
    """node name → worker pod IP, TTL-cached.

    Reference re-lists every request (main.go:68,171); we cache and
    refresh on miss so a just-scheduled worker is still found.
    """

    def __init__(self, kube: KubeClient, cfg=None, ttl_s: float = 10.0):
        self.kube = kube
        self.cfg = cfg or get_config()
        self.ttl_s = ttl_s
        self._cache: dict[str, str] = {}
        self._stamp = 0.0

    def _refresh(self) -> None:
        pods = self.kube.list_pods(
            self.cfg.worker_namespace,
            label_selector=self.cfg.worker_label_selector)
        cache: dict[str, str] = {}
        for pod_json in pods:
            p = Pod(pod_json)
            if p.node_name and p.pod_ip:
                cache[p.node_name] = p.pod_ip
        self._cache = cache
        self._stamp = time.monotonic()

    def registry_snapshot(self) -> dict[str, str]:
        self._refresh()
        return dict(self._cache)

    def worker_address(self, node_name: str) -> str | None:
        if time.monotonic() - self._stamp > self.ttl_s:
            self._refresh()
        ip = self._cache.get(node_name)
        if ip is None:
            self._refresh()  # cache miss: maybe a brand-new worker
            ip = self._cache.get(node_name)
        if ip is None:
            return None
        return f"{ip}:{self.cfg.worker_port}"


class _HttpError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(
        r"^/add(?:gpu|tpu)/namespace/(?P<ns>[^/]+)/pod/(?P<pod>[^/]+)"
        r"/(?:gpu|tpu)/(?P<num>[^/]+)/isEntireMount/(?P<entire>[^/]+)$"),
     "add"),
    ("POST", re.compile(
        r"^/remove(?:gpu|tpu)/namespace/(?P<ns>[^/]+)/pod/(?P<pod>[^/]+)"
        r"/force/(?P<force>[^/]+)$"),
     "remove"),
    ("GET", re.compile(r"^/$"), "index"),
    ("GET", re.compile(r"^/healthz$"), "healthz"),
    ("GET", re.compile(r"^/metrics$"), "metrics"),
    ("GET", re.compile(r"^/workers$"), "workers"),
    ("POST", re.compile(r"^/addslice$"), "addslice"),
    ("POST", re.compile(r"^/removeslice$"), "removeslice"),
]


def _parse_bool(raw: str, param: str) -> bool:
    low = raw.lower()
    if low in ("true", "1", "t"):
        return True
    if low in ("false", "0", "f"):
        return False
    raise _HttpError(400, f"Invalid param {param}: {raw} "
                          "(should be true or false)")


class MasterApp:
    """Transport-independent request handling; served by build_http_server."""

    def __init__(self, kube: KubeClient, cfg=None,
                 worker_client_factory=None,
                 registry: WorkerRegistry | None = None):
        self.cfg = cfg or get_config()
        self.kube = kube
        self.registry = registry or WorkerRegistry(kube, self.cfg)
        self._client_factory = worker_client_factory or (
            lambda addr: WorkerClient(addr))

    # --- plumbing ---

    def handle(self, method: str, path: str, body: bytes,
               headers: dict[str, str]) -> tuple[int, str, str]:
        """Returns (status, content_type, body)."""
        try:
            for m, pattern, name in _ROUTES:
                if m != method:
                    continue
                match = pattern.match(path)
                if match:
                    return getattr(self, f"_route_{name}")(match, body, headers)
            raise _HttpError(404, "404 page not found")
        except _HttpError as exc:
            return exc.status, "text/plain", exc.message + "\n"
        except Exception as exc:  # noqa: BLE001 — boundary
            logger.exception("unhandled error for %s %s", method, path)
            return 500, "text/plain", f"Service Internal Error: {exc}\n"

    def _worker_for_pod(self, namespace: str, pod_name: str) -> tuple[str, str]:
        """(worker_address, node_name); raises _HttpError on miss."""
        try:
            pod = Pod(self.kube.get_pod(namespace, pod_name))
        except NotFoundError:
            raise _HttpError(
                404, f"No pod: {pod_name} in namespace: {namespace}")
        node = pod.node_name
        if not node:
            raise _HttpError(400, f"Pod {pod_name} is not scheduled yet")
        address = self.registry.worker_address(node)
        if address is None:
            logger.error("no tpumounter worker on node %s", node)
            raise _HttpError(500, "Service Internal Error")
        return address, node

    # --- routes ---

    def _route_index(self, match, body, headers):
        return 200, "text/plain", "tpumounter master\n"

    def _route_healthz(self, match, body, headers):
        return 200, "text/plain", "ok\n"

    def _route_metrics(self, match, body, headers):
        return 200, "text/plain; version=0.0.4", REGISTRY.render()

    def _parse_slice_body(self, body: bytes):
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import SliceTarget
        try:
            payload = jsonlib.loads(body or b"{}")
        except ValueError:
            raise _HttpError(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _HttpError(400, 'body must be a JSON object with a '
                                  '"pods" list')
        raw = payload.get("pods")
        if not isinstance(raw, list) or not raw:
            raise _HttpError(400, 'JSON body needs "pods": '
                                  '[{"namespace": ..., "pod": ...}, ...]')
        targets = []
        for entry in raw:
            if not isinstance(entry, dict):
                raise _HttpError(400, f"pods entries must be objects "
                                      f'{{"namespace", "pod"}}: {entry!r}')
            ns = entry.get("namespace", "default")
            pod = entry.get("pod")
            if not pod:
                raise _HttpError(400, f"pods entry missing 'pod': {entry}")
            targets.append(SliceTarget(namespace=ns, pod=pod))
        return payload, targets

    def _slice_coordinator(self):
        from gpumounter_tpu.master.slice_ops import SliceCoordinator
        return SliceCoordinator(self.kube, self.registry,
                                self._client_factory, self.cfg)

    def _route_addslice(self, match, body, headers):
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import SliceError
        payload, targets = self._parse_slice_body(body)
        try:
            chips = int(payload.get("chipsPerHost", 4))
        except (TypeError, ValueError):
            raise _HttpError(400, f"Invalid chipsPerHost: "
                                  f"{payload.get('chipsPerHost')!r}")
        if chips <= 0:
            raise _HttpError(400, f"Invalid chipsPerHost: {chips}")
        entire = bool(payload.get("isEntireMount", True))
        try:
            plan = self._slice_coordinator().mount_slice(targets, chips,
                                                         entire)
        except SliceError as exc:
            raise _HttpError(exc.status, str(exc))
        return 200, "application/json", jsonlib.dumps(plan, indent=1) + "\n"

    def _route_removeslice(self, match, body, headers):
        import json as jsonlib

        from gpumounter_tpu.master.slice_ops import SliceError
        payload, targets = self._parse_slice_body(body)
        force = bool(payload.get("force", False))
        try:
            outcome = self._slice_coordinator().remove_slice(targets, force)
        except SliceError as exc:
            raise _HttpError(exc.status, str(exc))
        return 200, "application/json", jsonlib.dumps(outcome) + "\n"

    def _route_workers(self, match, body, headers):
        # Worker registry endpoint (no reference analog): node → worker IP.
        lines = [f"{node} {ip}" for node, ip in
                 sorted(self.registry.registry_snapshot().items())]
        return 200, "text/plain", "\n".join(lines) + "\n"

    def _route_add(self, match, body, headers):
        ns = match.group("ns")
        pod_name = match.group("pod")
        num_raw = match.group("num")
        try:
            tpu_num = int(num_raw)
        except ValueError:
            raise _HttpError(400, f"Invalid param gpuNum: {num_raw}")
        entire = _parse_bool(match.group("entire"), "isEntireMount")
        logger.info("AddTPU request: %s/%s num=%d entire=%s",
                    ns, pod_name, tpu_num, entire)
        address, node = self._worker_for_pod(ns, pod_name)
        with self._client_factory(address) as client:
            try:
                result = client.add_tpu(pod_name, ns, tpu_num, entire)
            except Exception as exc:  # noqa: BLE001 — gRPC boundary
                logger.error("worker AddTPU failed: %s", exc)
                raise _HttpError(500, f"Service Internal Error: {_grpc_detail(exc)}")
        if result == api.AddTPUResult.Success:
            return 200, "text/plain", "Add TPU Success\n"
        if result == api.AddTPUResult.InsufficientTPU:
            raise _HttpError(500, f"Insufficient TPU on Node: {node}")
        if result == api.AddTPUResult.PodNotFound:
            raise _HttpError(400, f"No Pod {pod_name} on Node: {node}")
        raise _HttpError(500, f"unknown worker result {result}")

    def _route_remove(self, match, body, headers):
        ns = match.group("ns")
        pod_name = match.group("pod")
        force = _parse_bool(match.group("force"), "force")
        form = urllib.parse.parse_qs(body.decode("utf-8", "replace"))
        raw_uuids = form.get("uuids")
        if not raw_uuids:
            raise _HttpError(400, "Invalid parameter")
        uuids: list[str] = []
        for entry in raw_uuids:  # repeated fields and comma-joined both work
            uuids.extend(u for u in entry.split(",") if u)
        logger.info("RemoveTPU request: %s/%s uuids=%s force=%s",
                    ns, pod_name, uuids, force)
        address, node = self._worker_for_pod(ns, pod_name)
        with self._client_factory(address) as client:
            try:
                result = client.remove_tpu(pod_name, ns, uuids, force)
            except Exception as exc:  # noqa: BLE001 — gRPC boundary
                logger.error("worker RemoveTPU failed: %s", exc)
                raise _HttpError(500, f"Service Internal Error: {_grpc_detail(exc)}")
        joined = ", ".join(uuids)
        if result == api.RemoveTPUResult.Success:
            return 200, "text/plain", f"Remove {len(uuids)} TPUs Success\n"
        if result == api.RemoveTPUResult.PodNotFound:
            raise _HttpError(400, f"No Pod {pod_name} on Node: {node}")
        if result == api.RemoveTPUResult.TPUBusy:
            raise _HttpError(
                400, f"Pod: {pod_name} has running processes on TPU: {joined}")
        if result == api.RemoveTPUResult.TPUNotFound:
            raise _HttpError(400, f"Invalid UUIDs: {joined}")
        raise _HttpError(500, f"unknown worker result {result}")


def _grpc_detail(exc: Exception) -> str:
    details = getattr(exc, "details", None)
    if callable(details):
        return str(details())
    return str(exc)


def build_http_server(app: MasterApp, port: int | None = None,
                      host: str = "0.0.0.0") -> ThreadingHTTPServer:
    cfg = app.cfg

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _dispatch(self):
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            status, ctype, text = app.handle(
                self.command, self.path, body, dict(self.headers))
            payload = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        do_GET = _dispatch
        do_POST = _dispatch

        def log_message(self, fmt, *args):
            logger.debug("http: " + fmt, *args)

    return ThreadingHTTPServer((host, port or cfg.master_port), Handler)
